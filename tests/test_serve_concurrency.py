"""High-concurrency object server (ISSUE 7): the shared pack-enumeration
cache (keyed, single-flighted, LRU-bounded, ref-update invalidated),
byte-range resume of torn fetch-pack streams, load shedding with
Retry-After, and the narrowed push lock under concurrent pushes."""

import json
import os
import threading
import time

import pytest

from kart_tpu import telemetry
from kart_tpu import transport
from kart_tpu.core.repo import KartRepo
from kart_tpu.transport.http import HttpRemote, HttpTransportError, make_server
from kart_tpu.transport.protocol import ObjectEnumerator
from kart_tpu.transport.remote import RemoteError
from kart_tpu.transport.retry import RETRY_AFTER_CAP, RetryPolicy

from helpers import edit_commit, make_imported_repo


@pytest.fixture(autouse=True)
def _fresh_metrics():
    """Each test reads counters from a clean registry (make_server enables
    metrics process-globally)."""
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setenv("KART_TRANSPORT_RETRY_BASE", "0.01")
    monkeypatch.setenv("KART_TRANSPORT_RETRY_CAP", "0.05")
    monkeypatch.delenv("KART_FAULTS", raising=False)
    monkeypatch.delenv("KART_SERVE_ENUM_CACHE", raising=False)
    monkeypatch.delenv("KART_SERVE_MAX_INFLIGHT", raising=False)


@pytest.fixture()
def served_repo(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=12)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    server = make_server(repo)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/"
    yield repo, ds_path, url
    server.shutdown()
    server.server_close()


def counter(name, **labels):
    for n, l, v in telemetry.snapshot()["counters"]:
        if n == name and l == labels:
            return v
    return 0


def fresh_dst(tmp_path, name):
    return KartRepo.init_repository(str(tmp_path / name))


# ---------------------------------------------------------------------------
# enum cache: single-flight, hits, invalidation, LRU
# ---------------------------------------------------------------------------


def test_second_concurrent_clone_same_key_runs_zero_extra_walks(
    served_repo, tmp_path, monkeypatch
):
    """ISSUE 7 acceptance: a second concurrent clone of the same
    (refs, filter) key performs ZERO additional ObjectEnumerator walks —
    it single-flights on the first walk and serves from the memo, asserted
    via both a walk counter and the cache's own counters."""
    from kart_tpu.transport import service

    repo, _, url = served_repo
    walks = []
    orig_iter = ObjectEnumerator.__iter__

    def counting_iter(enum):
        walks.append(1)
        time.sleep(0.6)  # hold the walk open so the peer provably overlaps
        return orig_iter(enum)

    monkeypatch.setattr(ObjectEnumerator, "__iter__", counting_iter)

    client = HttpRemote(url)
    wants = list(client.ls_refs()["heads"].values())
    dsts = [fresh_dst(tmp_path, "c1"), fresh_dst(tmp_path, "c2")]
    headers, errors = [None, None], []

    def fetch(i):
        try:
            c = HttpRemote(url)
            headers[i] = c.fetch_pack(dsts[i], wants)
        except Exception as e:  # kart: noqa(KTL006): re-raised below via the errors list — a bare thread would swallow the failure entirely
            errors.append(e)

    t1 = threading.Thread(target=fetch, args=(0,))
    t2 = threading.Thread(target=fetch, args=(1,))
    t1.start()
    time.sleep(0.15)  # t1 is inside its (slowed) walk when t2 arrives
    t2.start()
    t1.join()
    t2.join()
    assert not errors
    assert len(walks) == 1, "second concurrent clone re-ran the walk"
    assert counter("server.enum_cache.misses") == 1
    assert counter("server.enum_cache.singleflight_waits") == 1
    assert counter("server.enum_cache.hits") == 1
    # both clients received the complete, identical object set
    oids1 = sorted(dsts[0].odb.iter_oids())
    oids2 = sorted(dsts[1].odb.iter_oids())
    assert oids1 == oids2 and len(oids1) == headers[0]["object_count"]
    assert headers[0] == headers[1]


def test_sequential_repeat_fetch_hits_cache(served_repo, tmp_path, monkeypatch):
    repo, _, url = served_repo
    walks = []
    orig_iter = ObjectEnumerator.__iter__
    monkeypatch.setattr(
        ObjectEnumerator,
        "__iter__",
        lambda e: (walks.append(1), orig_iter(e))[1],
    )
    client = HttpRemote(url)
    wants = list(client.ls_refs()["heads"].values())
    a, b = fresh_dst(tmp_path, "a"), fresh_dst(tmp_path, "b")
    h1 = client.fetch_pack(a, wants)
    h2 = client.fetch_pack(b, wants)
    assert h1 == h2
    assert len(walks) == 1
    assert counter("server.enum_cache.hits") == 1
    assert counter("server.enum_cache.misses") == 1
    # the cached replay is byte-identical: both stores hold the same oids
    assert sorted(a.odb.iter_oids()) == sorted(b.odb.iter_oids())


def test_cache_disabled_by_env_still_serves(served_repo, tmp_path, monkeypatch):
    monkeypatch.setenv("KART_SERVE_ENUM_CACHE", "0")
    repo, _, url = served_repo
    client = HttpRemote(url)
    wants = list(client.ls_refs()["heads"].values())
    client.fetch_pack(fresh_dst(tmp_path, "a"), wants)
    client.fetch_pack(fresh_dst(tmp_path, "b"), wants)
    assert counter("server.enum_cache.hits") == 0
    assert counter("server.enum_cache.misses") == 0


def test_bad_filter_request_releases_the_fill_token(served_repo, tmp_path):
    """A pre-walk failure (malformed filter spec) must abandon the
    single-flight token: a repeated identical request fails fast instead
    of blocking on an event nobody will ever set."""
    repo, _, url = served_repo
    client = HttpRemote(url, retry=RetryPolicy(attempts=1))
    wants = list(client.ls_refs()["heads"].values())
    for attempt in range(2):
        t0 = time.monotonic()
        with pytest.raises(HttpTransportError):
            client.fetch_pack(
                fresh_dst(tmp_path, f"bad{attempt}"),
                wants,
                filter_spec="not-a-bbox",
            )
        assert time.monotonic() - t0 < 10, (
            "second identical bad request blocked on a leaked fill token"
        )
    # and the key is not poisoned for the cache bookkeeping either
    assert counter("server.enum_cache.hits") == 0


def test_ref_update_invalidates_cache(served_repo, tmp_path):
    """A push both re-keys (ref fingerprint) and drops stale entries — a
    client fetching after the push sees the new commit, never a stale
    memoized walk."""
    repo, ds_path, url = served_repo
    client = HttpRemote(url)
    wants = list(client.ls_refs()["heads"].values())
    client.fetch_pack(fresh_dst(tmp_path, "warm"), wants)
    assert counter("server.enum_cache.misses") == 1

    # push a new commit from a clone
    clone = transport.clone(url, tmp_path / "clone", do_checkout=False)
    clone.config.set_many({"user.name": "C", "user.email": "c@example.com"})
    new_oid = edit_commit(clone, ds_path, deletes=[2], message="edit")
    transport.push(clone, "origin")
    evictions = counter("server.enum_cache.evictions")
    assert evictions >= 1  # apply_ref_updates dropped the stale entries

    dst = fresh_dst(tmp_path, "after")
    new_wants = list(client.ls_refs()["heads"].values())
    assert new_wants == [new_oid]
    client.fetch_pack(dst, new_wants)
    assert dst.odb.contains(new_oid)


def test_lru_byte_budget_evicts(served_repo, tmp_path, monkeypatch):
    """KART_SERVE_ENUM_CACHE bounds the memo: a budget smaller than two
    entries evicts the older one (counted)."""
    monkeypatch.setenv("KART_SERVE_ENUM_CACHE", "2048")
    repo, _, url = served_repo
    client = HttpRemote(url)
    info = client.ls_refs()
    wants = list(info["heads"].values())
    # two different keys: a full fetch and a filtered variant (haves differ)
    client.fetch_pack(fresh_dst(tmp_path, "a"), wants)
    client.fetch_pack(fresh_dst(tmp_path, "b"), wants, haves=wants)
    assert counter("server.enum_cache.misses") == 2
    assert counter("server.enum_cache.evictions") >= 1


def test_oid_list_replay_tier_byte_identical(served_repo, tmp_path, monkeypatch):
    """Entries too big for the raw-bytes tier memoize the ordered oid list
    instead; the replay (no walk) produces the identical object set."""
    monkeypatch.setenv("KART_SERVE_ENUM_CACHE", "4096")  # bytes cap = 512
    repo, _, url = served_repo
    walks = []
    orig_iter = ObjectEnumerator.__iter__
    monkeypatch.setattr(
        ObjectEnumerator,
        "__iter__",
        lambda e: (walks.append(1), orig_iter(e))[1],
    )
    client = HttpRemote(url)
    wants = list(client.ls_refs()["heads"].values())
    a, b = fresh_dst(tmp_path, "a"), fresh_dst(tmp_path, "b")
    client.fetch_pack(a, wants)
    client.fetch_pack(b, wants)
    assert len(walks) == 1  # second serve replayed the recorded oid list
    assert counter("server.enum_cache.hits") == 1
    assert sorted(a.odb.iter_oids()) == sorted(b.odb.iter_oids())


# ---------------------------------------------------------------------------
# byte-range resume
# ---------------------------------------------------------------------------


def test_torn_fetch_resumes_mid_pack_by_byte_range(
    served_repo, tmp_path, monkeypatch
):
    """A client-side tear mid-packstream retries with Range/If-Range and
    the server answers 206 from the same deterministic enumeration — the
    stream continues at the exact record boundary, no restart."""
    repo, _, url = served_repo
    client = HttpRemote(url, retry=RetryPolicy(attempts=3, base_delay=0.01))
    wants = list(client.ls_refs()["heads"].values())
    dst = fresh_dst(tmp_path, "dst")
    monkeypatch.setenv("KART_FAULTS", "transport.read.frame:9")
    try:
        header = client.fetch_pack(dst, wants)
    finally:
        monkeypatch.delenv("KART_FAULTS", raising=False)
    assert counter("server.range_resumes") == 1
    assert counter("transport.range_resumes") == 1
    got = sum(1 for _ in dst.odb.iter_oids())
    assert got == header["object_count"]


def test_range_request_with_stale_validator_gets_full_response(
    served_repo, tmp_path
):
    """If-Range with a wrong etag must never splice two enumerations: the
    server falls back to a 200 full response."""
    import urllib.request

    repo, _, url = served_repo
    client = HttpRemote(url)
    wants = list(client.ls_refs()["heads"].values())
    body = json.dumps(
        {"wants": wants, "haves": [], "have_shallow": [], "depth": None,
         "filter": None, "exclude": []}
    ).encode()
    req = urllib.request.Request(
        url.rstrip("/") + "/api/v1/fetch-pack",
        data=body,
        headers={
            "Content-Type": "application/json",
            "Range": "bytes=64-",
            "If-Range": '"not-the-right-etag"',
        },
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers.get("ETag")
        data = resp.read()
    # a full framed response: starts with the 8-byte header length
    n = int.from_bytes(data[:8], "big")
    assert json.loads(data[8 : 8 + n])["object_count"] > 0


# ---------------------------------------------------------------------------
# load shedding
# ---------------------------------------------------------------------------


def test_inflight_ceiling_sheds_with_retry_after(served_repo, tmp_path, monkeypatch):
    """With KART_SERVE_MAX_INFLIGHT=1, a request arriving while another is
    being served gets 429 + Retry-After (and the client error carries it)."""
    monkeypatch.setenv("KART_SERVE_MAX_INFLIGHT", "1")
    monkeypatch.setenv("KART_SERVE_RETRY_AFTER", "7")
    repo, _, url = served_repo
    release = threading.Event()
    entered = threading.Event()
    orig_iter = ObjectEnumerator.__iter__

    def slow_iter(enum):
        entered.set()
        release.wait(10)
        return orig_iter(enum)

    monkeypatch.setattr(ObjectEnumerator, "__iter__", slow_iter)
    client = HttpRemote(url, retry=RetryPolicy(attempts=1))
    wants = list(client.ls_refs()["heads"].values())

    t = threading.Thread(
        target=lambda: HttpRemote(url, retry=RetryPolicy(attempts=1)).fetch_pack(
            fresh_dst(tmp_path, "slow"), wants
        ),
    )
    t.start()
    try:
        assert entered.wait(10)
        with pytest.raises(HttpTransportError) as exc:
            client.ls_refs()
        assert exc.value.transient  # 429 is retryable
        assert exc.value.retry_after == 7.0
        assert counter("server.shed") == 1
        # observability of an overloaded server is the point: the stats
        # endpoint bypasses admission control and still answers
        from kart_tpu.cli.stats_cmds import fetch_remote_stats

        assert "kart_server_shed_total 1" in fetch_remote_stats(url)
    finally:
        release.set()
        t.join()


def test_retry_after_floors_backoff():
    """RetryPolicy honours a server-sent Retry-After as the backoff floor,
    capped, and never *lowers* a larger exponential delay."""
    def run(retry_after, base=0.01, attempts=2):
        sleeps = []
        policy = RetryPolicy(attempts=attempts, base_delay=base, sleep=sleeps.append)
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < attempts:
                raise HttpTransportError(
                    "shed", transient=True, retry_after=retry_after
                )
            return "ok"

        assert policy.call(fn) == "ok"
        return sleeps

    # floor: the header wins over a tiny exponential delay
    assert run(5.0) == [5.0]
    # cap: a hostile header can't park the client beyond RETRY_AFTER_CAP
    assert run(10_000.0) == [RETRY_AFTER_CAP]
    # a larger computed backoff is kept (the header is a floor, not a cap)
    sleeps = run(0.001, base=2.0)
    assert sleeps == [2.0]
    # absent/garbage headers change nothing
    assert run(None) == [0.01]


def test_retry_after_header_parsed_seconds_form_only(served_repo, monkeypatch):
    from kart_tpu.transport.http import _retry_after_of

    class _E:
        def __init__(self, headers):
            self.headers = headers

    assert _retry_after_of(_E({"Retry-After": "3"})) == 3.0
    assert _retry_after_of(_E({"Retry-After": "2.5"})) == 2.5
    assert _retry_after_of(_E({"Retry-After": "Wed, 21 Oct 2015"})) is None
    assert _retry_after_of(_E({})) is None
    assert _retry_after_of(_E({"Retry-After": "-1"})) is None


# ---------------------------------------------------------------------------
# narrowed push lock: concurrent pushes
# ---------------------------------------------------------------------------


def _snapshot_store(repo):
    import hashlib

    objects_dir = repo.odb.objects_dir
    snap = {}
    for root, dirs, files in os.walk(objects_dir):
        if "quarantine" in root:
            continue
        for name in files:
            p = os.path.join(root, name)
            with open(p, "rb") as f:
                snap[os.path.relpath(p, objects_dir)] = hashlib.sha256(
                    f.read()
                ).hexdigest()
    return snap


def test_concurrent_pushes_to_different_branches_both_land(
    served_repo, tmp_path
):
    """The push lock covers only ref validation + migrate: two pushes to
    *different* branches drain their quarantines concurrently and both
    land."""
    repo, ds_path, url = served_repo
    results, errors = {}, []

    def push_branch(i):
        try:
            clone = transport.clone(
                url, tmp_path / f"clone{i}", do_checkout=False
            )
            clone.config.set_many(
                {"user.name": f"C{i}", "user.email": f"c{i}@example.com"}
            )
            oid = edit_commit(
                clone, ds_path, deletes=[i + 1], message=f"edit {i}"
            )
            results[i] = (oid, transport.push(clone, "origin", [f"main:b{i}"]))
        except Exception as e:  # kart: noqa(KTL006): re-raised below via the errors list — a bare thread would swallow the failure entirely
            errors.append(e)

    threads = [
        threading.Thread(target=push_branch, args=(i,)) for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for i in range(2):
        oid, updated = results[i]
        assert updated == {f"refs/heads/b{i}": oid}
        assert repo.refs.get(f"refs/heads/b{i}") == oid
        assert repo.odb.contains(oid)


def test_contended_same_ref_push_both_land_via_rebase(served_repo, tmp_path):
    """ISSUE 9: the CAS loser no longer bounces — the server rebases it
    onto the winner's tip inside the quarantine and lands it. Zero
    client-visible CAS failures; both edits reachable from the final
    tip."""
    repo, ds_path, url = served_repo
    outcomes, oids = [], {}

    def push_main(i):
        try:
            clone = transport.clone(
                url, tmp_path / f"w{i}", do_checkout=False
            )
            clone.config.set_many(
                {"user.name": f"W{i}", "user.email": f"w{i}@example.com"}
            )
            oids[i] = edit_commit(
                clone, ds_path, deletes=[i + 3], message=f"race {i}"
            )
            transport.push(clone, "origin")
            outcomes.append("ok")
        except RemoteError:
            outcomes.append("conflict")

    threads = [threading.Thread(target=push_main, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert outcomes == ["ok", "ok"]
    tip = repo.refs.get("refs/heads/main")
    for oid in oids.values():
        assert repo.is_ancestor(oid, tip)
    # both deletes are present in the merged tip
    fids = {f["fid"] for f in repo.datasets("HEAD")[ds_path].features()}
    assert 3 not in fids and 4 not in fids


def test_rejected_conflicting_push_leaves_store_byte_identical(
    served_repo, tmp_path
):
    """A contended push whose rebase hits *real* conflicts is rejected with
    the structured report: the loser's quarantine (including the merge
    classifier's scratch trees and temp refs) is discarded and the served
    store is byte-identical to the winner-only state — zero debris for gc
    to sweep."""
    repo, ds_path, url = served_repo
    # both clones start from the same tip and edit the SAME feature
    c1 = transport.clone(url, tmp_path / "c1", do_checkout=False)
    c2 = transport.clone(url, tmp_path / "c2", do_checkout=False)
    for i, c in enumerate((c1, c2)):
        c.config.set_many(
            {"user.name": f"P{i}", "user.email": f"p{i}@example.com"}
        )
    edit_commit(
        c1, ds_path,
        updates=[{"fid": 5, "geom": None, "name": "winner", "rating": 1.0}],
        message="winner",
    )
    edit_commit(
        c2, ds_path,
        updates=[{"fid": 5, "geom": None, "name": "loser", "rating": 2.0}],
        message="loser",
    )
    transport.push(c1, "origin")
    before = _snapshot_store(repo)
    tip_before = repo.refs.get("refs/heads/main")
    with pytest.raises(RemoteError, match="conflict"):
        transport.push(c2, "origin")
    assert _snapshot_store(repo) == before
    assert repo.refs.get("refs/heads/main") == tip_before
    quarantine = os.path.join(repo.odb.objects_dir, "quarantine")
    assert not os.path.isdir(quarantine) or os.listdir(quarantine) == []
