"""Runtime fallback coverage: every production dispatcher must produce
identical results with no usable jax backend (wedged-accelerator scenario,
VERDICT r1 weak #2)."""

import numpy as np
import pytest

import kart_tpu.runtime as runtime
from kart_tpu.ops.blocks import FeatureBlock, pack_oid_hex
from kart_tpu.ops.bbox import bbox_intersects, bbox_intersects_np
from kart_tpu.ops.diff_kernel import (
    classify_blocks,
    classify_blocks_reference,
    INSERT,
    UPDATE,
    DELETE,
)
from kart_tpu.ops.merge_kernel import (
    merge_classify,
    merge_classify_reference,
)


def _block(pk_to_oid):
    keys = np.asarray(sorted(pk_to_oid), dtype=np.int64)
    oids = pack_oid_hex([pk_to_oid[int(k)] for k in keys])
    paths = [f"p/{k}" for k in keys]
    return FeatureBlock.from_arrays(keys, oids, paths)


def _oid(i):
    return f"{i:040x}"


@pytest.fixture
def no_jax(monkeypatch):
    """Simulate an unusable backend without touching process-global state."""
    monkeypatch.setattr(runtime, "_probe_result", {
        "ok": False,
        "backend": None,
        "device_kind": None,
        "n_devices": 0,
        "init_seconds": 0.0,
        "error": "simulated outage",
    })
    assert not runtime.jax_ready()


def test_classify_blocks_fallback_matches_reference(no_jax):
    old = _block({1: _oid(1), 2: _oid(2), 3: _oid(3), 5: _oid(5)})
    new = _block({2: _oid(2), 3: _oid(33), 4: _oid(4), 5: _oid(5)})
    old_class, new_class, counts = classify_blocks(old, new)
    ref_old, ref_new = classify_blocks_reference(old, new)
    np.testing.assert_array_equal(old_class, ref_old)
    np.testing.assert_array_equal(new_class, ref_new)
    assert counts == {"inserts": 1, "updates": 1, "deletes": 1}
    assert int(np.sum(new_class == INSERT)) == 1
    assert int(np.sum(old_class == UPDATE)) == 1
    assert int(np.sum(old_class == DELETE)) == 1


def test_merge_classify_fallback_matches_reference(no_jax):
    anc = _block({1: _oid(1), 2: _oid(2), 3: _oid(3), 4: _oid(4)})
    ours = _block({1: _oid(1), 2: _oid(21), 3: _oid(3), 5: _oid(5)})  # edit 2, del 4, add 5
    theirs = _block({1: _oid(1), 2: _oid(22), 3: _oid(3), 4: _oid(44)})  # edit 2 (conflict), edit 4
    union, decision, presence, stats = merge_classify(anc, ours, theirs)
    ref_union, ref_decision = merge_classify_reference(anc, ours, theirs)
    np.testing.assert_array_equal(union, ref_union)
    np.testing.assert_array_equal(decision, ref_decision)
    # 2: both edited differently -> conflict; 4: deleted vs edited -> conflict
    assert stats["conflicts"] == 2
    # presence bits: a=1, o=2, t=4; key 5 is ours-only
    assert presence[list(union).index(5)] == 2
    assert presence[list(union).index(4)] == 1 | 4


def test_merge_classify_fallback_matches_device_path(no_jax, monkeypatch):
    """The numpy fallback must agree with the jitted kernel bit-for-bit; run
    the same inputs through both (jit path via a fresh ready probe). The
    small-input threshold is lowered so the second call genuinely jits."""
    import kart_tpu.ops.diff_kernel as diff_kernel

    monkeypatch.setattr(diff_kernel, "DEVICE_MIN_ROWS", 0)
    # the cost model routes CPU backends to the host engine; force the
    # device kernel so this test genuinely jits
    monkeypatch.setenv("KART_DIFF_DEVICE", "1")
    rng = np.random.default_rng(42)
    pks = rng.choice(10_000, size=300, replace=False)
    anc = _block({int(k): _oid(int(k)) for k in pks})
    ours = _block(
        {int(k): _oid(int(k) + (1 if k % 7 == 0 else 0)) for k in pks if k % 11 != 0}
    )
    theirs = _block(
        {int(k): _oid(int(k) + (2 if k % 5 == 0 else 0)) for k in pks if k % 13 != 0}
    )
    union_f, dec_f, pres_f, stats_f = merge_classify(anc, ours, theirs)

    runtime._probe_result = None  # drop the simulated outage: jit path
    try:
        assert runtime.jax_ready()
        union_j, dec_j, pres_j, stats_j = merge_classify(anc, ours, theirs)
    finally:
        runtime._probe_result = None
    np.testing.assert_array_equal(union_f, union_j)
    np.testing.assert_array_equal(dec_f, dec_j)
    np.testing.assert_array_equal(pres_f, pres_j)
    assert stats_f == stats_j


def test_bbox_fallback_matches_reference(no_jax):
    envelopes = np.asarray(
        [
            [-10, -10, 10, 10],
            [100, 20, 120, 40],
            [170, -5, -170, 5],  # anti-meridian wrap
        ],
        dtype=np.float64,
    )
    query = (0.0, 0.0, 5.0, 5.0)
    got = bbox_intersects(envelopes, query)
    np.testing.assert_array_equal(got, bbox_intersects_np(envelopes, query))


def test_insulate_updates_device_count_in_flags(monkeypatch):
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    import os

    runtime.insulate_virtual_cpu(8)
    assert "--xla_force_host_platform_device_count=8" in os.environ["XLA_FLAGS"]
    assert "=2" not in os.environ["XLA_FLAGS"]


def test_reprobe_adopts_slow_init(monkeypatch):
    """A probe that timed out but whose init thread later finished must be
    adopted by reprobe() (slow-not-wedged); a still-stuck thread updates the
    failure record with the total wait."""
    import threading
    import time as _time

    # slow: the "init thread" finishes during the extra wait
    done = threading.Event()

    def fake_init():
        done.wait()

    t = threading.Thread(target=fake_init, daemon=True)
    t.start()
    box = {}
    monkeypatch.setattr(runtime, "_probe_result", {
        "ok": False, "backend": None, "device_kind": None, "n_devices": 0,
        "init_seconds": 1.0, "error": "backend init timed out after 1.0s",
    })
    monkeypatch.setattr(runtime, "_probe_thread", t)
    monkeypatch.setattr(runtime, "_probe_box", box)
    box["result"] = {
        "ok": True, "backend": "tpu", "device_kind": "TPU v5",
        "n_devices": 1, "init_seconds": 3.0, "error": None,
    }
    done.set()
    info = runtime.reprobe(5)
    assert info["ok"] and info["backend"] == "tpu"
    assert runtime.probe_backend()["ok"]  # cached as the live result

    # wedged: thread never finishes within the wait
    stuck = threading.Event()
    t2 = threading.Thread(target=stuck.wait, daemon=True)
    t2.start()
    monkeypatch.setattr(runtime, "_probe_result", {
        "ok": False, "backend": None, "device_kind": None, "n_devices": 0,
        "init_seconds": 1.0, "error": "backend init timed out after 1.0s",
    })
    monkeypatch.setattr(runtime, "_probe_thread", t2)
    monkeypatch.setattr(runtime, "_probe_box", {})
    info = runtime.reprobe(0.05)
    assert not info["ok"]
    assert "wedged" in info["error"]
    stuck.set()


def test_reprobe_noop_on_success(monkeypatch):
    monkeypatch.setattr(runtime, "_probe_result", {
        "ok": True, "backend": "cpu", "device_kind": "cpu", "n_devices": 1,
        "init_seconds": 0.1, "error": None,
    })
    monkeypatch.setattr(runtime, "_probe_thread", None)
    assert runtime.reprobe(1)["ok"]
