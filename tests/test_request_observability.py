"""Tier-1 tests for ISSUE 12 — request-scoped observability: cross-process
trace propagation (traceparent header/frame field, retry attempts sharing
one request id, HTTP/stdio parity), bucketed latency histograms with
quantile estimates, slow-request exemplars, the JSON-lines access log,
windowed rates, ``kart top``, the mergeable client+server Chrome traces,
and the trace-buffer saturation counter."""

import io
import json
import os
import stat
import sys
import threading
import time

import pytest

from helpers import make_imported_repo
from kart_tpu import telemetry
from kart_tpu.telemetry import access, context, core, sinks


@pytest.fixture(autouse=True)
def clean_registry():
    telemetry.reset()
    yield
    telemetry.reset()


# -- trace context ----------------------------------------------------------


def test_traceparent_roundtrip():
    with telemetry.request_scope(verb="fetch-pack") as ctx:
        wire = ctx.traceparent()
        assert context.parse_traceparent(wire) == (
            ctx.trace_id,
            ctx.request_id,
        )
    # malformed values never break request handling
    for bad in (None, "", "garbage", "00-xyz-abc-01", 42, "00-" + "a" * 31):
        assert context.parse_traceparent(bad) is None


def test_verb_scopes_inherit_the_root_trace_id():
    root = telemetry.set_root_request(verb="clone")
    with telemetry.request_scope(verb="ls-refs") as a:
        assert a.trace_id == root.trace_id
        assert a.request_id != root.request_id
        assert a.parent_id == root.request_id
    with telemetry.request_scope(verb="fetch-pack") as b:
        assert b.trace_id == root.trace_id
        assert b.request_id != a.request_id


def test_server_scope_adopts_wire_ids():
    with telemetry.request_scope(verb="fetch-pack") as client_ctx:
        wire = client_ctx.traceparent()
    with telemetry.request_scope(verb="fetch-pack", traceparent=wire) as srv:
        # the server's telemetry is labelled with the ORIGINATING ids
        assert srv.trace_id == client_ctx.trace_id
        assert srv.request_id == client_ctx.request_id


def test_server_scope_without_traceparent_mints_fresh_trace():
    """A request arriving WITHOUT a traceparent (legacy client) must mint
    a fresh trace — never fold unrelated clients into the serving
    process's own root context (the servers pass inherit=False)."""
    root = telemetry.set_root_request(verb="serve")
    with telemetry.request_scope(
        verb="fetch-pack", traceparent=None, inherit=False
    ) as a:
        pass
    with telemetry.request_scope(
        verb="fetch-pack", traceparent=None, inherit=False
    ) as b:
        pass
    assert a.trace_id != root.trace_id
    assert b.trace_id != root.trace_id
    assert a.trace_id != b.trace_id  # two clients never share a trace
    assert a.parent_id is None


def test_annotate_reaches_the_access_record():
    with telemetry.request_scope(verb="x") as ctx:
        telemetry.annotate(shed=True, enum_cache="hit", nothing=None)
        record = access.record_request(verb="x", status=429, seconds=0.01)
    assert record["shed"] is True
    assert record["enum_cache"] == "hit"
    assert "nothing" not in record
    assert record["request_id"] == ctx.request_id


def test_span_exit_records_into_request_tree():
    telemetry.enable(metrics=True)
    with telemetry.request_scope(verb="x", record=True) as ctx:
        with telemetry.span("server.enum_walk"):
            with telemetry.span("odb.read_blobs_batch"):
                pass
    names = [e["name"] for e in ctx.span_tree()]
    assert names == ["odb.read_blobs_batch", "server.enum_walk"]
    assert all(e["dur"] >= 0 and e["start"] >= 0 for e in ctx.span_tree())
    # unrecorded scopes stay empty (no per-span cost when not armed)
    with telemetry.request_scope(verb="y") as ctx2:
        with telemetry.span("server.enum_walk"):
            pass
    assert ctx2.span_tree() == []


def test_request_tree_is_bounded(monkeypatch):
    telemetry.enable(metrics=True)
    monkeypatch.setattr(context, "REQUEST_EVENT_CAP", 3)
    with telemetry.request_scope(verb="x", record=True) as ctx:
        for _ in range(10):
            with telemetry.span("diff.classify"):
                pass
    assert len(ctx.events) == 3
    assert ctx.events_dropped == 7


# -- bucketed histograms + quantiles ----------------------------------------


def _bucket_of(value):
    from bisect import bisect_left

    return bisect_left(core.BUCKET_BOUNDS, value)


def test_quantile_estimates_within_bucket_error():
    """Estimates against exact percentiles of a known sample: the estimate
    must land in the same log bucket as the exact value (the documented
    error bound)."""
    import random

    import numpy as np

    telemetry.enable(metrics=True)
    rng = random.Random(42)
    values = [rng.lognormvariate(-3.0, 1.5) for _ in range(5000)]
    for v in values:
        telemetry.observe("server.request_seconds", v, verb="fetch-pack")
    ((_, _, h),) = telemetry.snapshot()["histograms"]
    for q, est in ((50, h["p50"]), (90, h["p90"]), (99, h["p99"])):
        exact = float(np.percentile(values, q))
        assert _bucket_of(est) == _bucket_of(exact), (q, est, exact)
        assert h["min"] <= est <= h["max"]
    # buckets are cumulative and end at +Inf == count
    assert h["buckets"][-1] == ["+Inf", len(values)]
    counts = [c for _le, c in h["buckets"]]
    assert counts == sorted(counts)


def test_quantiles_exact_for_single_observation():
    telemetry.enable(metrics=True)
    telemetry.observe("server.request_seconds", 0.3, verb="x")
    ((_, _, h),) = telemetry.snapshot()["histograms"]
    # clamped to the observed range: a single sample reports itself
    assert h["p50"] == h["p99"] == pytest.approx(0.3)


def test_prometheus_histogram_exposition():
    telemetry.enable(metrics=True)
    for v in (0.003, 0.003, 0.7):
        telemetry.observe("server.request_seconds", v, verb="fetch-pack")
    text = sinks.prometheus_text()
    assert "# TYPE kart_server_request_seconds histogram" in text
    assert (
        'kart_server_request_seconds_bucket{le="0.005",verb="fetch-pack"} 2'
        in text
    )
    assert (
        'kart_server_request_seconds_bucket{le="+Inf",verb="fetch-pack"} 3'
        in text
    )
    assert 'kart_server_request_seconds_count{verb="fetch-pack"} 3' in text


def test_span_aggregates_carry_buckets_too():
    telemetry.enable(metrics=True)
    with telemetry.span("server.enum_walk"):
        time.sleep(0.002)
    hists = {n: h for n, _l, h in telemetry.snapshot()["histograms"]}
    assert hists["server.enum_walk"]["buckets"][-1][1] == 1
    assert hists["server.enum_walk"]["p99"] > 0


# -- trace-buffer saturation (satellite) ------------------------------------


def test_event_buffer_saturation_is_counted(monkeypatch, caplog, tmp_path):
    monkeypatch.setattr(core, "_EVENT_CAP", 4)
    path = str(tmp_path / "trace.json")
    telemetry.enable(metrics=True, trace=True, trace_path=path)
    with caplog.at_level("WARNING", logger="kart_tpu.telemetry.core"):
        for _ in range(10):
            with telemetry.span("diff.classify"):
                pass
    assert telemetry.events_dropped_count() == 6
    counters = dict(telemetry.counters_snapshot())
    assert counters[("telemetry.events_dropped", ())] == 6
    warnings = [r for r in caplog.records if "dropped" in r.getMessage()]
    assert len(warnings) == 1  # one warning, not one per drop
    # the export summary surfaces the drop count as a metadata event
    assert sinks.write_chrome_trace() == path
    doc = json.load(open(path))
    metas = [
        e for e in doc["traceEvents"] if e["name"] == "kart_events_dropped"
    ]
    assert metas and metas[0]["args"]["dropped"] == 6


def test_fork_child_dump_failure_warns(tmp_path, caplog):
    telemetry.enable(
        trace=True, trace_path=str(tmp_path / "no-such-dir" / "t.json")
    )
    with telemetry.span("diff.classify"):
        pass
    with caplog.at_level("WARNING", logger="kart_tpu.telemetry.core"):
        telemetry.dump_fork_child()
    assert any(
        "side-file" in r.getMessage() and "not written" in r.getMessage()
        for r in caplog.records
    )


def test_sidecar_merge_failure_warns(tmp_path, caplog):
    path = str(tmp_path / "trace.json")
    telemetry.enable(trace=True, trace_path=path)
    with telemetry.span("diff.classify"):
        pass
    side = f"{path}.child-999"
    with open(side, "w") as f:
        f.write("not json")
    with caplog.at_level("WARNING", logger="kart_tpu.telemetry.sinks"):
        assert sinks.write_chrome_trace() == path
    assert any("unreadable" in r.getMessage() for r in caplog.records)
    assert not os.path.exists(side)


# -- access log / windows helpers -------------------------------------------


def test_env_parsing(monkeypatch):
    monkeypatch.delenv("KART_SLOW_REQUEST_SECONDS", raising=False)
    assert access.slow_threshold() is None
    monkeypatch.setenv("KART_SLOW_REQUEST_SECONDS", "0")
    assert access.slow_threshold() is None
    monkeypatch.setenv("KART_SLOW_REQUEST_SECONDS", "garbage")
    assert access.slow_threshold() is None
    monkeypatch.setenv("KART_SLOW_REQUEST_SECONDS", "2.5")
    assert access.slow_threshold() == 2.5
    monkeypatch.setenv("KART_STATS_WINDOWS", "5, 30,junk,")
    assert access.stats_windows() == (5.0, 30.0)
    monkeypatch.delenv("KART_STATS_WINDOWS", raising=False)
    assert access.stats_windows() == access.DEFAULT_WINDOWS


def test_window_rates_decay_when_idle(monkeypatch):
    telemetry.enable(metrics=True)
    monkeypatch.setattr(access, "_SAMPLE_MIN_INTERVAL", 0.0)
    t = [1000.0]
    telemetry.incr("transport.server.requests", verb="fetch-pack")
    access._maybe_sample(t[0])
    telemetry.incr("transport.server.requests", verb="fetch-pack")
    rates = access.window_rates(now=t[0] + 2.0)
    entry = [
        r
        for r in rates["10s"]
        if r[0] == "transport.server.requests"
    ]
    assert entry and entry[0][2] == pytest.approx(0.5)  # 1 req / 2s
    # nothing new: the rate decays toward zero as time passes
    rates = access.window_rates(now=t[0] + 8.0)
    entry = [r for r in rates["10s"] if r[0] == "transport.server.requests"]
    assert entry and entry[0][2] == pytest.approx(1 / 8.0)


# -- HTTP end-to-end ---------------------------------------------------------


def _start_http_server(repo):
    from kart_tpu.transport.http import make_server

    server = make_server(repo)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}/"


def test_http_propagation_retry_ladder_and_access_log(
    tmp_path, monkeypatch
):
    """A torn-and-resumed HTTP fetch: both server-side attempts of the one
    logical fetch-pack share the client's request id, every access-log
    line carries the root trace id, and the annotations name the cache
    decision — the ISSUE 12 propagation acceptance, HTTP side."""
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.transport.http import HttpRemote
    from kart_tpu.transport.retry import RetryPolicy

    log_path = str(tmp_path / "access.jsonl")
    monkeypatch.setenv("KART_ACCESS_LOG", log_path)
    repo, _ = make_imported_repo(tmp_path, n=600)
    server, url = _start_http_server(repo)
    try:
        dst = KartRepo.init_repository(str(tmp_path / "dst"))
        client = HttpRemote(url, retry=RetryPolicy(attempts=3, base_delay=0.01))
        root = telemetry.set_root_request(verb="clone")
        wants = list(client.ls_refs()["heads"].values())
        monkeypatch.setenv("KART_FAULTS", "transport.read.frame:200")
        try:
            client.fetch_pack(dst, wants)
        finally:
            monkeypatch.delenv("KART_FAULTS", raising=False)
    finally:
        server.shutdown()
        server.server_close()

    records = [json.loads(line) for line in open(log_path)]
    by_verb = {}
    for r in records:
        by_verb.setdefault(r["verb"], []).append(r)
    # the torn fetch-pack retried: two wire requests, ONE request id
    fp = by_verb["fetch-pack"]
    assert len(fp) == 2
    assert len({r["request_id"] for r in fp}) == 1
    assert fp[1].get("range_resume") is True
    assert fp[0]["enum_cache"] == "miss"
    # every line joins the client's one trace
    assert {r["trace_id"] for r in records} == {root.trace_id}
    # ls-refs has its own request id, same trace
    assert by_verb["ls-refs"][0]["request_id"] != fp[0]["request_id"]
    for r in records:
        assert r["status"] in (200, 206)
        assert r["seconds"] >= 0
        assert r["bytes_out"] > 0


def test_slow_request_exemplar_names_the_slow_frame(tmp_path, monkeypatch):
    """An (injected-threshold) slow request is captured as an exemplar
    whose span tree names the frame that cost the time, served via the
    stats endpoint."""
    from urllib.request import urlopen

    from kart_tpu.core.repo import KartRepo
    from kart_tpu.transport.http import HttpRemote

    monkeypatch.setenv("KART_SLOW_REQUEST_SECONDS", "0.000001")
    repo, _ = make_imported_repo(tmp_path, n=50)
    server, url = _start_http_server(repo)
    try:
        dst = KartRepo.init_repository(str(tmp_path / "dst"))
        client = HttpRemote(url)
        client.fetch_pack(dst, list(client.ls_refs()["heads"].values()))
        with urlopen(url + "api/v1/stats?format=json", timeout=10) as resp:
            payload = json.loads(resp.read().decode())
    finally:
        server.shutdown()
        server.server_close()

    exemplars = [e for e in payload["exemplars"] if e["verb"] == "fetch-pack"]
    assert exemplars
    ex = exemplars[0]
    assert ex["slow"] is True
    assert ex["request_id"]
    names = {s["name"] for s in ex["spans"]}
    # the tree names the walk that cost the time, under the request anchor
    assert "transport.request" in names
    assert "server.enum_walk" in names
    # counted as a metric too
    counters = {
        (n, labels.get("verb")): v
        for n, labels, v in payload["snapshot"]["counters"]
    }
    assert counters.get(("server.slow_requests", "fetch-pack"), 0) >= 1
    # the JSON stats document carries the live inflight gauge
    assert "inflight" in payload


def test_storm_server_percentiles_agree_with_clients(tmp_path, monkeypatch):
    """Concurrent clients: the server-side per-verb p50/p99 from the
    bucketed histograms agree with the client-observed percentiles within
    the one-bucket error bound — the ISSUE 12 storm acceptance, sized for
    tier-1. The enum cache is disabled so every request pays the full
    walk+spool+stream server-side (a cache-hit memcpy decouples the
    server's handler time from the client's drain via socket buffering —
    the bench's big-pack storm keeps the cache on instead). The storm is
    sized to the host: the agreement bound is about measurement, not
    capacity, and 16 client threads contending for one core queue on
    *client-side* unpack work the server never sees (observed p99 gap
    5x on a 1-core box), so clients scale with cores up to the full 16."""
    import math
    from urllib.request import urlopen

    from kart_tpu.core.repo import KartRepo
    from kart_tpu.transport.http import HttpRemote

    n_clients = min(16, max(4, 2 * (os.cpu_count() or 1)))
    monkeypatch.setenv("KART_SERVE_ENUM_CACHE", "0")
    repo, _ = make_imported_repo(tmp_path, n=1500)
    server, url = _start_http_server(repo)
    durations = []
    dur_lock = threading.Lock()
    errors = []

    def client_run(i):
        try:
            client = HttpRemote(url)
            dst = KartRepo.init_repository(str(tmp_path / f"c{i}"))
            wants = list(client.ls_refs()["heads"].values())
            t0 = time.perf_counter()
            client.fetch_pack(dst, wants)
            with dur_lock:
                durations.append(time.perf_counter() - t0)
        except Exception as e:  # surfaced below: the storm must be clean
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=client_run, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with urlopen(url + "api/v1/stats?format=json", timeout=10) as resp:
            payload = json.loads(resp.read().decode())
    finally:
        server.shutdown()
        server.server_close()

    assert not errors, errors
    assert len(durations) == n_clients
    hist = None
    for n, labels, h in payload["snapshot"]["histograms"]:
        if n == "server.request_seconds" and labels.get("verb") == "fetch-pack":
            hist = h
    assert hist is not None and hist["count"] == n_clients
    ordered = sorted(durations)
    for q, est in ((0.50, hist["p50"]), (0.99, hist["p99"])):
        idx = min(len(ordered) - 1, math.ceil(q * len(ordered)) - 1)
        client_q = ordered[idx]
        # agreement within one log bucket (the documented error bound)
        assert abs(_bucket_of(est) - _bucket_of(client_q)) <= 1, (
            q,
            est,
            client_q,
        )


def test_kart_top_renders_live_view(tmp_path, cli_runner):
    from kart_tpu.cli import cli
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.transport.http import HttpRemote

    repo, _ = make_imported_repo(tmp_path, n=50)
    server, url = _start_http_server(repo)
    try:
        client = HttpRemote(url)
        dst = KartRepo.init_repository(str(tmp_path / "dst"))
        client.fetch_pack(dst, list(client.ls_refs()["heads"].values()))
        r = cli_runner.invoke(cli, ["top", "--once", url])
    finally:
        server.shutdown()
        server.server_close()
    assert r.exit_code == 0, r.output
    assert "fetch-pack" in r.output
    assert "p99" in r.output
    assert "inflight" in r.output
    assert "req/s(10s)" in r.output


# -- stdio parity ------------------------------------------------------------


def _install_fake_ssh(tmp_path, monkeypatch, extra_env=""):
    """The test_ssh_transport stub: a fake `ssh` executing the remote
    command locally (optionally exporting extra env for the server side
    only), plus a `kart` shim on PATH."""
    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    kart = bindir / "kart"
    kart.write_text(
        "#!/bin/sh\n"
        f"PYTHONPATH={os.path.dirname(os.path.dirname(os.path.abspath(__file__)))} "
        f'exec {sys.executable} -m kart_tpu.cli "$@"\n'
    )
    kart.chmod(kart.stat().st_mode | stat.S_IEXEC)
    fake_ssh = bindir / "fake-ssh"
    fake_ssh.write_text(
        "#!/bin/sh\n"
        "shift\n"
        f'{extra_env}exec sh -c "$*"\n'
    )
    fake_ssh.chmod(fake_ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("KART_SSH", str(fake_ssh))
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")


def test_stdio_propagation_parity(tmp_path, monkeypatch):
    """The stdio transport carries the same request id end-to-end as HTTP:
    the spawned server's access-log records adopt the client's ids, retry
    attempts share one id, and responses echo the traceparent."""
    from kart_tpu.transport.stdio import StdioRemote
    from kart_tpu.transport.retry import RetryPolicy
    from kart_tpu.core.repo import KartRepo

    _install_fake_ssh(tmp_path, monkeypatch)
    log_path = str(tmp_path / "access.jsonl")
    monkeypatch.setenv("KART_ACCESS_LOG", log_path)
    (tmp_path / "server").mkdir()
    repo, _ = make_imported_repo(tmp_path / "server", n=600)
    url = f"testhost:{repo.workdir or repo.gitdir}"

    root = telemetry.set_root_request(verb="clone")
    client = StdioRemote(url, retry=RetryPolicy(attempts=3, base_delay=0.01))
    try:
        dst = KartRepo.init_repository(str(tmp_path / "dst"))
        wants = list(client.ls_refs()["heads"].values())
        # tear the client-side drain mid-stream: the retry respawns the
        # server process and must present the SAME request id (the fresh
        # server process never reaches this many frame reads itself).
        # 201, not 200: the faults module re-arms on spec *change*, and an
        # earlier test in this file already fired :200 in this process
        monkeypatch.setenv("KART_FAULTS", "transport.read.frame:201")
        try:
            client.fetch_pack(dst, wants)
        finally:
            monkeypatch.delenv("KART_FAULTS", raising=False)
    finally:
        client.close()

    deadline = time.monotonic() + 10
    records = []
    while time.monotonic() < deadline:
        if os.path.exists(log_path):
            records = [json.loads(line) for line in open(log_path)]
            if len([r for r in records if r["verb"] == "fetch-pack"]) >= 2:
                break
        time.sleep(0.1)
    fp = [r for r in records if r["verb"] == "fetch-pack"]
    assert len(fp) == 2  # two attempts (two server processes)...
    assert len({r["request_id"] for r in fp}) == 1  # ...one logical request
    assert {r["trace_id"] for r in records} == {root.trace_id}
    ls = [r for r in records if r["verb"] == "ls-refs"]
    assert ls and ls[0]["request_id"] != fp[0]["request_id"]
    for r in records:
        assert r["status"] == "ok"
        assert r["bytes_out"] > 0


def test_stdio_response_echoes_traceparent_and_stats_json(tmp_path):
    from kart_tpu.transport.http import read_framed, write_framed
    from kart_tpu.transport.stdio import serve_stdio

    repo, _ = make_imported_repo(tmp_path, n=5)
    with telemetry.request_scope(verb="stats") as ctx:
        req = io.BytesIO()
        # two ops on one connection: the refs op books its request record
        # BEFORE the stats op reads the registry
        write_framed(req, {"op": "refs"}, ())
        write_framed(
            req,
            {
                "op": "stats",
                "format": "json",
                "traceparent": ctx.traceparent(),
            },
            (),
        )
        req.seek(0)
        out = io.BytesIO()
        serve_stdio(repo, req, out)
        out.seek(0)
        _refs_resp, fp = read_framed(out)
        from kart_tpu.transport.pack import read_pack

        for _ in read_pack(fp):
            pass
        resp, _fp = read_framed(out)
    assert resp["traceparent"] == ctx.traceparent()
    snap = resp["stats"]["snapshot"]
    hist_verbs = {
        labels.get("verb")
        for n, labels, _h in snap["histograms"]
        if n == "server.request_seconds"
    }
    assert "ls-refs" in hist_verbs
    assert "rates" in resp["stats"]


# -- mergeable client + server Chrome traces ---------------------------------


def test_merge_rebases_timestamps_onto_one_clock(tmp_path):
    """Each trace's ts values are offsets from its own process's enable
    instant; the merge re-bases them via the kart_trace_epoch anchors, so
    a server enabled an hour before the client still lines up."""

    def write_trace(path, epoch_unix, ts):
        json.dump(
            {
                "traceEvents": [
                    {"name": "transport.request", "ph": "X", "ts": ts,
                     "dur": 5.0, "pid": 1 if epoch_unix < 2000 else 2,
                     "tid": 1, "args": {}},
                    {"name": "kart_trace_epoch", "ph": "M", "pid": 9,
                     "tid": 0, "args": {"unix": epoch_unix}},
                ]
            },
            open(path, "w"),
        )

    early = str(tmp_path / "server.json")   # enabled at unix t=1000
    late = str(tmp_path / "client.json")    # enabled at unix t=4600
    write_trace(early, 1000.0, ts=3_600_000_000.0)  # event 3600s in
    write_trace(late, 4600.0, ts=0.0)               # event at its t=0
    out = str(tmp_path / "merged.json")
    sinks.merge_chrome_traces(out, [early, late])
    doc = json.load(open(out))
    spans = {
        e["pid"]: e["ts"]
        for e in doc["traceEvents"]
        if e.get("ph") == "X"
    }
    # both events happened at the same wall-clock instant: after
    # re-basing they carry the same merged timestamp
    assert spans[1] == pytest.approx(spans[2])


def test_client_and_server_traces_merge_on_request_ids(
    tmp_path, monkeypatch, cli_runner
):
    """``kart --trace clone`` (client, in-process CLI) against a spawned
    serve-stdio with ``KART_TRACE`` (server subprocess): the two Chrome
    traces share trace/request ids and merge into one timeline."""
    from kart_tpu.cli import cli

    server_trace = str(tmp_path / "server-trace.json")
    _install_fake_ssh(
        tmp_path, monkeypatch, extra_env=f"KART_TRACE={server_trace} "
    )
    client_trace = str(tmp_path / "client-trace.json")
    monkeypatch.setenv("KART_TRACE", client_trace)
    (tmp_path / "server").mkdir()
    repo, _ = make_imported_repo(tmp_path / "server", n=40)
    url = f"testhost:{repo.workdir or repo.gitdir}"

    r = cli_runner.invoke(
        cli, ["clone", "--bare", url, str(tmp_path / "clone")]
    )
    assert r.exit_code == 0, r.output

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not os.path.exists(server_trace):
        time.sleep(0.1)
    client_doc = json.load(open(client_trace))
    server_doc = json.load(open(server_trace))

    def ids(doc, key):
        return {
            e["args"][key]
            for e in doc["traceEvents"]
            if e.get("ph") == "X" and key in e.get("args", {})
        }

    client_pids = {e["pid"] for e in client_doc["traceEvents"]}
    server_pids = {e["pid"] for e in server_doc["traceEvents"]}
    assert client_pids.isdisjoint(server_pids)  # separate lanes
    # the join: one shared trace id, overlapping request ids
    assert ids(client_doc, "trace_id") == ids(server_doc, "trace_id")
    assert len(ids(client_doc, "trace_id")) == 1
    shared_requests = ids(client_doc, "request_id") & ids(
        server_doc, "request_id"
    )
    assert shared_requests  # the verbs' ids appear on both sides
    # the server's per-request anchor spans carry originating ids; the
    # fetch-pack one (the verb with client-side spans) joins the client
    # trace. (The refs op's id is minted client-side too, but the client
    # records no spans during ls-refs, so only the server trace shows it.)
    anchors = [
        e
        for e in server_doc["traceEvents"]
        if e.get("name") == "transport.request"
    ]
    assert anchors
    anchor_ids = {a["args"]["request_id"] for a in anchors}
    assert anchor_ids & ids(client_doc, "request_id")

    merged = str(tmp_path / "merged.json")
    n = sinks.merge_chrome_traces(merged, [client_trace, server_trace])
    doc = json.load(open(merged))
    assert len(doc["traceEvents"]) == n
    assert {e["pid"] for e in doc["traceEvents"]} >= (
        client_pids | server_pids
    )
