"""Mechanical SQL dialect validation for the server-DB working copies.

VERDICT r3 weak #5: the golden-SQL snapshots prove *stability*, not that
the emitted DDL/DML is valid in its dialect — a syntactically invalid
trigger body would pass. No live servers and no sqlglot exist in this
environment, so this is a purpose-built checker that fails on the defect
classes a wrong-dialect emission actually produces:

* lexical errors: unterminated strings/comments/quotes, quoting syntax the
  dialect doesn't have (backticks outside MySQL, ``[brackets]`` outside
  T-SQL, ``$tag$`` bodies outside PostgreSQL, double-quoted *identifiers*
  in MySQL — where ``"x"`` is a string literal by default and silently
  changes meaning);
* unbalanced parens/brackets inside a statement;
* parameter-marker style mismatches (``%s`` is the psycopg/pymysql style,
  ``?`` is pyodbc's — each driver rejects the other's);
* statement heads the dialect has no grammar for (``REPLACE INTO`` outside
  MySQL, ``ON CONFLICT`` outside PostgreSQL, ``MERGE``/``IF``/``EXEC``
  preambles outside T-SQL, ...);
* column type names from the wrong dialect's type system;
* trigger scaffolding missing the dialect's mandatory clauses
  (PG: FOR EACH ROW + EXECUTE PROCEDURE/FUNCTION; MySQL: timing + event +
  FOR EACH ROW; T-SQL: ON <table> AFTER ... AS).

It is NOT a full SQL parser; expression-level nonsense can still slip
through. Every check it does make is backed by a poison test
(tests/test_sql_dialects.py) proving it fails on the wrong dialect's
output and on seeded syntax errors.
"""

import re

PG = "postgres"
MYSQL = "mysql"
MSSQL = "tsql"


class SqlDialectError(ValueError):
    pass


def _err(dialect, msg, context=""):
    ctx = f" near {context[:60]!r}" if context else ""
    raise SqlDialectError(f"[{dialect}] {msg}{ctx}")


WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_$#]*")
NUM_RE = re.compile(r"\d+(\.\d+)?")
DOLLAR_TAG_RE = re.compile(r"\$[A-Za-z_]*\$")


def tokenize(sql, dialect):
    """-> list of (kind, text) tokens. kind in: word, string, ident, num,
    param, punct. Raises SqlDialectError on lexical errors for the
    dialect."""
    out = []
    i = 0
    n = len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j == -1 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j == -1:
                _err(dialect, "unterminated block comment", sql[i:])
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            while True:
                if j >= n:
                    _err(dialect, "unterminated string literal", sql[i:])
                if sql[j] == "\\" and dialect == MYSQL and j + 1 < n:
                    j += 2
                    continue
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            out.append(("string", sql[i : j + 1]))
            i = j + 1
            continue
        if c == '"':
            if dialect == MYSQL:
                # without ANSI_QUOTES, MySQL reads "x" as a STRING — an
                # emitted double-quoted identifier silently changes meaning
                _err(
                    dialect,
                    'double-quoted identifier (MySQL treats "x" as a '
                    "string literal; use backticks)",
                    sql[i:],
                )
            j = i + 1
            while True:
                if j >= n:
                    _err(dialect, "unterminated quoted identifier", sql[i:])
                if sql[j] == '"':
                    if j + 1 < n and sql[j + 1] == '"':
                        j += 2
                        continue
                    break
                j += 1
            out.append(("ident", sql[i : j + 1]))
            i = j + 1
            continue
        if c == "`":
            if dialect != MYSQL:
                _err(dialect, "backtick identifier outside MySQL", sql[i:])
            j = sql.find("`", i + 1)
            if j == -1:
                _err(dialect, "unterminated backtick identifier", sql[i:])
            out.append(("ident", sql[i : j + 1]))
            i = j + 1
            continue
        if c == "[":
            if dialect == MSSQL:
                j = sql.find("]", i + 1)
                if j == -1:
                    _err(dialect, "unterminated [identifier]", sql[i:])
                out.append(("ident", sql[i : j + 1]))
                i = j + 1
                continue
            out.append(("punct", c))
            i += 1
            continue
        if c == "$":
            m = DOLLAR_TAG_RE.match(sql, i)
            if m:
                if dialect != PG:
                    _err(dialect, "dollar-quoted body outside PostgreSQL", sql[i:])
                tag = m.group(0)
                j = sql.find(tag, m.end())
                if j == -1:
                    _err(dialect, f"unterminated {tag} body", sql[i:])
                out.append(("string", sql[i : j + len(tag)]))
                i = j + len(tag)
                continue
            if dialect == PG and i + 1 < n and sql[i + 1].isdigit():
                j = i + 1
                while j < n and sql[j].isdigit():
                    j += 1
                out.append(("param", sql[i:j]))
                i = j
                continue
            _err(dialect, "stray '$'", sql[i:])
        if sql.startswith("%s", i):
            if dialect == MSSQL:
                _err(dialect, "'%s' parameter (pyodbc uses '?')", sql[i:])
            out.append(("param", "%s"))
            i += 2
            continue
        if c == "?":
            if dialect != MSSQL:
                _err(
                    dialect,
                    "'?' parameter (psycopg/pymysql use '%s')",
                    sql[i:],
                )
            out.append(("param", "?"))
            i += 1
            continue
        m = WORD_RE.match(sql, i)
        if m:
            out.append(("word", m.group(0)))
            i = m.end()
            continue
        m = NUM_RE.match(sql, i)
        if m:
            out.append(("num", m.group(0)))
            i = m.end()
            continue
        out.append(("punct", c))
        i += 1
    return out


def split_statements(tokens, dialect):
    """Top-level ';' split. BEGIN...END blocks (trigger/procedure bodies in
    MySQL and T-SQL) keep their internal semicolons inside one statement."""
    stmts = []
    cur = []
    depth = 0
    begin_depth = 0
    for kind, text in tokens:
        up = text.upper() if kind == "word" else text
        if kind == "punct" and text == "(":
            depth += 1
        elif kind == "punct" and text == ")":
            depth -= 1
            if depth < 0:
                _err(dialect, "unbalanced ')'")
        elif kind == "word" and up == "BEGIN":
            begin_depth += 1
        elif kind == "word" and up == "END":
            if begin_depth > 0:
                begin_depth -= 1
        if kind == "punct" and text == ";" and depth == 0 and begin_depth == 0:
            if cur:
                stmts.append(cur)
                cur = []
            continue
        cur.append((kind, text))
    if depth != 0:
        _err(dialect, "unbalanced '(' at end of input")
    if cur:
        stmts.append(cur)
    return stmts


# statement-head grammars: regex over the leading WORD tokens (uppercased)
_COMMON_HEADS = [
    r"CREATE TABLE",
    r"CREATE (UNIQUE )?INDEX",
    r"CREATE SCHEMA",
    r"DROP (TABLE|TRIGGER|INDEX|SCHEMA|FUNCTION|VIEW)",
    r"INSERT INTO",
    r"UPDATE",
    r"DELETE FROM",
    r"SELECT",
    r"ALTER TABLE",
    r"TRUNCATE",
]
HEADS = {
    PG: _COMMON_HEADS
    + [
        r"CREATE (OR REPLACE )?FUNCTION",
        r"CREATE TRIGGER",
        r"COMMENT ON",
        r"VACUUM",
        r"SET",  # session config, e.g. SET intervalstyle = 'iso_8601'
    ],
    MYSQL: _COMMON_HEADS
    + [
        r"CREATE SPATIAL INDEX",
        r"CREATE DATABASE",
        r"CREATE TRIGGER",
        r"CREATE (OR REPLACE )?SPATIAL REFERENCE SYSTEM",
        r"REPLACE INTO",
        r"SET",
        r"DROP DATABASE",
    ],
    MSSQL: _COMMON_HEADS
    + [
        r"CREATE SPATIAL INDEX",
        r"CREATE TRIGGER",
        r"IF",
        r"EXEC",
        r"DECLARE",
        r"MERGE",
        r"SET",
        # T-SQL trigger suspension during incremental reset:
        # DISABLE/ENABLE TRIGGER <name> ON <table>.  These statement heads
        # exist only in T-SQL (PG spells it ALTER TABLE ... DISABLE TRIGGER;
        # MySQL has no trigger suspension at all).
        r"DISABLE TRIGGER",
        r"ENABLE TRIGGER",
    ],
}

# tokens that only exist in some OTHER dialect's grammar / type system
POISON_WORDS = {
    PG: {
        "NVARCHAR", "DATETIME2", "DATETIMEOFFSET", "VARBINARY", "LONGTEXT",
        "LONGBLOB", "AUTO_INCREMENT", "TINYINT",
    },
    MYSQL: {
        "BYTEA", "TIMESTAMPTZ", "BIGSERIAL", "SERIAL", "NVARCHAR",
        "DATETIME2", "DATETIMEOFFSET", "PLPGSQL",
    },
    MSSQL: {
        "BYTEA", "TIMESTAMPTZ", "BIGSERIAL", "SERIAL", "AUTO_INCREMENT",
        "LONGTEXT", "LONGBLOB", "BOOLEAN", "PLPGSQL",
    },
}
POISON_PHRASES = {
    PG: [r"\bREPLACE INTO\b", r"\bON DUPLICATE KEY\b"],
    MYSQL: [r"\bON CONFLICT\b", r"\bRETURNS TRIGGER\b", r"::"],
    MSSQL: [r"\bON CONFLICT\b", r"\bREPLACE INTO\b", r"\bFOR EACH ROW\b"],
}

# column-spec type whitelists (the "column specs" golden section); each
# entry is a regex matched against the full type expression
TYPE_SPECS = {
    PG: [
        r"BIGSERIAL", r"SERIAL", r"BIGINT", r"INTEGER", r"SMALLINT",
        r"GEOMETRY\([A-Z]+,\d+\)", r"GEOMETRY", r"BOOLEAN", r"BYTEA",
        r"DATE", r"REAL", r"DOUBLE PRECISION", r"NUMERIC(\(\d+,\d+\))?",
        r"TEXT", r"VARCHAR\(\d+\)", r"TIME", r"TIMESTAMPTZ", r"TIMESTAMP",
    ],
    MYSQL: [
        r"BIGINT( AUTO_INCREMENT)?", r"INT", r"SMALLINT", r"TINYINT",
        r"(GEOMETRY|POINT|LINESTRING|POLYGON|MULTIPOINT|MULTILINESTRING|"
        r"MULTIPOLYGON|GEOMETRYCOLLECTION)( SRID \d+)?",
        r"BIT", r"LONGBLOB", r"DATE", r"FLOAT", r"DOUBLE( PRECISION)?",
        r"NUMERIC(\(\d+,\d+\))?", r"LONGTEXT", r"VARCHAR\(\d+\)", r"TIME",
        r"TIMESTAMP", r"DATETIME",
    ],
    MSSQL: [
        r"BIGINT", r"INT", r"SMALLINT", r"TINYINT",
        r"GEOMETRY( CHECK\(.*\))*", r"BIT", r"VARBINARY\((max|\d+)\)",
        r"DATE", r"REAL", r"FLOAT", r"NUMERIC(\(\d+,\d+\))?",
        r"NVARCHAR\((max|\d+)\)", r"TIME", r"DATETIMEOFFSET", r"DATETIME2",
    ],
}


def _head_words(stmt_tokens, limit=5):
    words = []
    for kind, text in stmt_tokens:
        if kind == "word":
            words.append(text.upper())
        else:
            break
        if len(words) >= limit:
            break
    return " ".join(words)


def _stmt_text(stmt_tokens):
    return " ".join(t for _, t in stmt_tokens)


def check_statement(stmt_tokens, dialect):
    head = _head_words(stmt_tokens)
    if not head:
        _err(dialect, "statement does not start with a keyword",
             _stmt_text(stmt_tokens))
    if not any(re.match(h, head) for h in HEADS[dialect]):
        _err(dialect, f"statement head {head.split()[0]!r} not in the "
             f"{dialect} grammar", _stmt_text(stmt_tokens))

    upper_words = {t.upper() for k, t in stmt_tokens if k == "word"}
    bad = upper_words & POISON_WORDS[dialect]
    if bad:
        _err(dialect, f"foreign-dialect token(s) {sorted(bad)}",
             _stmt_text(stmt_tokens))
    joined = " ".join(
        (t.upper() if k == "word" else t) for k, t in stmt_tokens
    )
    for phrase in POISON_PHRASES[dialect]:
        if re.search(phrase, joined):
            _err(dialect, f"foreign-dialect construct /{phrase}/",
                 _stmt_text(stmt_tokens))

    # trigger scaffolding
    if re.match(r"CREATE TRIGGER", head):
        if dialect == PG:
            if "FOR EACH ROW" not in joined and "FOR EACH STATEMENT" not in joined:
                _err(dialect, "PG trigger without FOR EACH ROW/STATEMENT", joined)
            if not re.search(r"EXECUTE (PROCEDURE|FUNCTION)", joined):
                _err(dialect, "PG trigger without EXECUTE PROCEDURE/FUNCTION", joined)
        elif dialect == MYSQL:
            if not re.search(r"(BEFORE|AFTER) (INSERT|UPDATE|DELETE) ON", joined):
                _err(dialect, "MySQL trigger without timing+event", joined)
            if "FOR EACH ROW" not in joined:
                _err(dialect, "MySQL trigger without FOR EACH ROW", joined)
        elif dialect == MSSQL:
            if not re.search(r"ON .* (AFTER|INSTEAD OF) ", joined):
                _err(dialect, "T-SQL trigger without ON ... AFTER/INSTEAD OF", joined)
            if " AS " not in joined:
                _err(dialect, "T-SQL trigger without AS body", joined)
    if dialect == MSSQL and re.match(r"(DISABLE|ENABLE) TRIGGER", head):
        if " ON " not in joined:
            _err(dialect, "T-SQL DISABLE/ENABLE TRIGGER without ON <table>",
                 joined)
    if dialect == PG and re.match(r"CREATE (OR REPLACE )?FUNCTION", head):
        if re.search(r"RETURNS TRIGGER", joined) and "LANGUAGE" not in upper_words:
            _err(dialect, "PG trigger function without LANGUAGE clause", joined)


def check_column_spec(line, dialect):
    """One 'IDENT TYPE...' column-spec line."""
    tokens = tokenize(line, dialect)
    if not tokens or tokens[0][0] != "ident":
        _err(dialect, "column spec must start with a quoted identifier", line)
    rest = tokens[1:]
    # reassemble the type expression, normalising space around punctuation
    type_expr = re.sub(
        r"\s*([(),.])\s*", r"\1", " ".join(t for _, t in rest)
    ).strip()
    for spec in TYPE_SPECS[dialect]:
        if re.fullmatch(spec, type_expr, flags=re.IGNORECASE):
            return
    _err(dialect, f"type {type_expr!r} is not a {dialect} column type", line)


def check_sql(sql, dialect):
    """Validate a stream of statements; raises SqlDialectError."""
    tokens = tokenize(sql, dialect)
    for stmt in split_statements(tokens, dialect):
        check_statement(stmt, dialect)


def check_golden_file(text, dialect):
    """Validate a golden working-copy SQL file (sectioned format)."""
    section = None
    sql_lines = []
    for line in text.splitlines():
        if line.startswith("-- "):
            section = line[3:]
            continue
        if not line.strip():
            continue
        if section and section.startswith("column specs"):
            check_column_spec(line, dialect)
        else:
            sql_lines.append(line)
    check_sql("\n".join(sql_lines), dialect)
