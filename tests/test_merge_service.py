"""Contended-ref write service (ISSUE 9): server-side auto-rebase of
CAS-losing pushes, the per-ref FIFO merge queue, structured terminal
conflict rejection (byte-identical to a local `kart merge --dry-run -o
json`), the RetryPolicy terminal/paced split, and the refname hygiene a
server-constructed ref could trip."""

import json
import os
import threading

import pytest

from kart_tpu import telemetry, transport
from kart_tpu.core.repo import KartRepo
from kart_tpu.transport import service
from kart_tpu.transport.http import HttpRemote, HttpTransportError, make_server
from kart_tpu.transport.protocol import (
    ObjectEnumerator,
    Rejection,
    error_attrs_from_wire,
    rejection_wire_fields,
)
from kart_tpu.transport.remote import RemoteError
from kart_tpu.transport.retry import RetryPolicy, is_terminal

from helpers import edit_commit, make_imported_repo


@pytest.fixture(autouse=True)
def _fresh_metrics():
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setenv("KART_TRANSPORT_RETRY_BASE", "0.01")
    monkeypatch.setenv("KART_TRANSPORT_RETRY_CAP", "0.05")
    monkeypatch.delenv("KART_FAULTS", raising=False)
    monkeypatch.delenv("KART_SERVE_REBASE_ATTEMPTS", raising=False)
    monkeypatch.delenv("KART_SERVE_MERGE_QUEUE", raising=False)


@pytest.fixture()
def served_repo(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=16)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    server = make_server(repo)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/"
    yield repo, ds_path, url
    server.shutdown()
    server.server_close()


def counter(name, **labels):
    for n, l, v in telemetry.snapshot()["counters"]:
        if n == name and l == labels:
            return v
    return 0


def make_clone(url, tmp_path, name):
    clone = transport.clone(url, tmp_path / name, do_checkout=False)
    clone.config.set_many(
        {"user.name": name, "user.email": f"{name}@example.com"}
    )
    return clone


def raw_receive(url, repo, new_oid, *, old_oid, ref="refs/heads/main",
                retry=None):
    """Drive receive-pack directly (bypassing transport.push) so tests can
    pick the CAS base and read the full response payload."""
    from kart_tpu.transport.http import have_closure
    from kart_tpu.transport.remote import read_shallow

    client = HttpRemote(url, retry=retry or RetryPolicy(attempts=1))
    info = client.ls_refs()
    server_refs = {f"refs/heads/{b}": o for b, o in info["heads"].items()}
    has = have_closure(
        repo.odb, list(server_refs.values()), info.get("shallow", ())
    )
    enum = ObjectEnumerator(
        repo.odb, [new_oid], has=has.__contains__,
        sender_shallow=read_shallow(repo),
    )
    return client.receive_pack(
        enum,
        [{"ref": ref, "old": old_oid, "new": new_oid, "force": False}],
        shallow=lambda: enum.shallow_boundary,
    )


# ---------------------------------------------------------------------------
# the tier-1 merge-storm smoke: K=4 in-process writers, one branch
# ---------------------------------------------------------------------------


def test_four_writer_storm_all_land_zero_client_failures(served_repo, tmp_path):
    """ISSUE 9 acceptance (tier-1 scale): K=4 writers hammering one branch
    with disjoint-feature commits all land with zero client-visible CAS
    failures — the losers are rebased server-side and ordered through the
    merge queue — and every edit is reachable from the final tip."""
    repo, ds_path, url = served_repo
    K = 4
    outcomes, oids, errors = [], {}, []

    def writer(i):
        try:
            clone = make_clone(url, tmp_path, f"w{i}")
            oids[i] = edit_commit(
                clone, ds_path, deletes=[i + 1], message=f"writer {i}"
            )
            transport.push(clone, "origin")
            outcomes.append("ok")
        except Exception as e:  # kart: noqa(KTL006): re-raised below via the errors list — a bare thread would swallow the failure entirely
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert outcomes == ["ok"] * K
    tip = repo.refs.get("refs/heads/main")
    for oid in oids.values():
        assert repo.is_ancestor(oid, tip)
    fids = {f["fid"] for f in repo.datasets("HEAD")[ds_path].features()}
    assert fids.isdisjoint({1, 2, 3, 4})  # all four deletes landed
    # at least K-1 pushes went through the rebase path, none conflicted
    assert counter("server.rebase.landed") >= 1
    assert counter("server.rebase.conflicts") == 0
    assert counter("server.rebase.exhausted") == 0


# ---------------------------------------------------------------------------
# rebase outcome modes: merge / fast-forward / noop
# ---------------------------------------------------------------------------


def test_stale_cas_fast_forwards_when_incoming_contains_tip(
    served_repo, tmp_path
):
    """A push whose CAS base is stale but whose commit already *contains*
    the current tip fast-forwards — no merge commit is created."""
    repo, ds_path, url = served_repo
    base = repo.refs.get("refs/heads/main")
    clone = make_clone(url, tmp_path, "ff")
    c1 = edit_commit(clone, ds_path, deletes=[1], message="c1")
    transport.push(clone, "origin")  # tip is now c1
    c2 = edit_commit(clone, ds_path, deletes=[2], message="c2")
    # push c2 claiming the ORIGINAL base as CAS base: stale, but c2 ⊇ tip
    result = raw_receive(url, clone, c2, old_oid=base)
    assert result["updated"] == {"refs/heads/main": c2}
    assert result["rebase"]["rebased"] == 1
    assert result["rebase"]["mode"] == "ff"
    assert repo.refs.get("refs/heads/main") == c2


def test_stale_cas_noop_when_incoming_already_merged(served_repo, tmp_path):
    """Re-pushing a commit the tip already contains lands as a no-op: the
    ref stays at the current tip, nothing is created."""
    repo, ds_path, url = served_repo
    clone = make_clone(url, tmp_path, "noop")
    c1 = edit_commit(clone, ds_path, deletes=[1], message="c1")
    transport.push(clone, "origin")
    c2 = edit_commit(clone, ds_path, deletes=[2], message="c2")
    transport.push(clone, "origin")  # tip is c2 (contains c1)
    result = raw_receive(url, clone, c1, old_oid="0" * 40)
    assert result["updated"] == {"refs/heads/main": c2}
    assert result["rebase"]["mode"] == "noop"
    assert repo.refs.get("refs/heads/main") == c2


def test_rebased_merge_commit_shape_and_store_integrity(served_repo, tmp_path):
    """The landed merge commit: first parent = the tip that won, second =
    the incoming commit; tree carries both edits; every object (including
    the server-made commit) migrated from quarantine into the live store."""
    repo, ds_path, url = served_repo
    w1 = make_clone(url, tmp_path, "w1")
    w2 = make_clone(url, tmp_path, "w2")
    o1 = edit_commit(w1, ds_path, deletes=[1], message="w1")
    o2 = edit_commit(w2, ds_path, deletes=[2], message="w2")
    transport.push(w1, "origin")
    updated = transport.push(w2, "origin")
    tip = repo.refs.get("refs/heads/main")
    assert updated == {"refs/heads/main": tip}
    merge = repo.odb.read_commit(tip)
    assert merge.parents == (o1, o2)
    assert "server-side rebase" in merge.message
    fids = {f["fid"] for f in repo.datasets("HEAD")[ds_path].features()}
    assert 1 not in fids and 2 not in fids
    # the clone's tracking ref must stay RESOLVABLE: the server-made merge
    # commit was never downloaded, so tracking falls back to our own commit
    # (an ancestor of the true tip — behind, never dangling)
    track = w2.refs.get("refs/remotes/origin/main")
    assert track == o2
    assert w2.odb.contains(track)
    # a later fetch fast-forwards tracking to the real tip
    transport.fetch(w2, "origin")
    assert w2.refs.get("refs/remotes/origin/main") == tip
    assert w2.odb.contains(tip)
    assert service.merge_queue_for(repo) is service.merge_queue_for(repo)


# ---------------------------------------------------------------------------
# structured conflict rejection + parity with local `kart merge --dry-run`
# ---------------------------------------------------------------------------


def _conflicting_pair(served_repo, tmp_path):
    repo, ds_path, url = served_repo
    w1 = make_clone(url, tmp_path, "winner")
    w2 = make_clone(url, tmp_path, "loser")
    edit_commit(
        w1, ds_path,
        updates=[{"fid": 5, "geom": None, "name": "winner", "rating": 1.0}],
        message="winner",
    )
    loser_oid = edit_commit(
        w2, ds_path,
        updates=[{"fid": 5, "geom": None, "name": "loser", "rating": 2.0}],
        message="loser",
    )
    transport.push(w1, "origin")
    return repo, ds_path, url, w2, loser_oid


def test_conflict_rejection_is_terminal_single_attempt(served_repo, tmp_path):
    """Overlapping-feature contention rejects with the structured report
    after exactly ONE wire attempt — the terminal flag must defeat even a
    generous retry policy (the ISSUE 9 retry-amplification bug)."""
    repo, ds_path, url, loser, loser_oid = _conflicting_pair(
        served_repo, tmp_path
    )
    base = loser.refs.get("refs/remotes/origin/main")
    sleeps = []
    policy = RetryPolicy(attempts=5, base_delay=0.01, sleep=sleeps.append)
    with pytest.raises(HttpTransportError) as exc:
        raw_receive(url, loser, loser_oid, old_oid=base, retry=policy)
    e = exc.value
    assert e.terminal and is_terminal(e)
    assert not e.transient
    assert sleeps == []  # exactly one attempt, zero backoff sleeps
    report = e.conflict_report
    assert report["ref"] == "refs/heads/main"
    assert report["ours"] == loser_oid
    assert report["theirs"] == repo.refs.get("refs/heads/main")
    assert report["conflicts_total"] == 1
    body = report["merge"]["kart.merge/v1"]
    assert body["conflicts"] == {ds_path: {"feature": 1}}
    assert body["state"] == "merging" and body["dryRun"] is True


def test_conflict_report_parity_with_local_merge_dry_run(served_repo, tmp_path):
    """Satellite: the server's structured report must be byte-identical
    JSON to what the losing client computes locally with
    `kart merge <tip> --dry-run -o json` over the same two commits — one
    source of truth for the summary."""
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    repo, ds_path, url, loser, loser_oid = _conflicting_pair(
        served_repo, tmp_path
    )
    base = loser.refs.get("refs/remotes/origin/main")
    with pytest.raises(HttpTransportError) as exc:
        raw_receive(url, loser, loser_oid, old_oid=base)
    report = exc.value.conflict_report

    # the losing client's local view of the same merge
    transport.fetch(loser, "origin")
    tip = report["theirs"]
    r = CliRunner().invoke(
        cli,
        ["-C", loser.gitdir, "merge", tip, "--dry-run", "-o", "json"],
        catch_exceptions=False,
    )
    assert r.exit_code == 0, r.output
    local_doc = json.loads(r.output)
    assert json.dumps(report["merge"], sort_keys=False) == json.dumps(
        local_doc, sort_keys=False
    )


def test_conflict_rendered_like_local_merge(served_repo, tmp_path):
    """transport.push surfaces the report as the same hierarchical text a
    local merge conflict prints (dataset + part + count)."""
    repo, ds_path, url, loser, _ = _conflicting_pair(served_repo, tmp_path)
    with pytest.raises(RemoteError) as exc:
        transport.push(loser, "origin")
    text = str(exc.value)
    assert f"{ds_path}:" in text
    assert "feature:" in text and "1 conflicts" in text
    assert "kart merge" in text  # tells the human the local recourse
    assert "\x1b[" not in text  # unstyled: this is an exception message


# ---------------------------------------------------------------------------
# the busy lane: bounded CAS attempts + merge-queue overflow shed
# ---------------------------------------------------------------------------


def test_cas_budget_exhausted_is_paced_retryable_not_terminal(
    served_repo, tmp_path, monkeypatch
):
    """KART_SERVE_REBASE_ATTEMPTS=1 turns any stale CAS into the busy
    rejection: 429 + Retry-After, shed (so even receive-pack retries it,
    paced), never terminal."""
    monkeypatch.setenv("KART_SERVE_REBASE_ATTEMPTS", "1")
    monkeypatch.setenv("KART_SERVE_RETRY_AFTER", "2")
    repo, ds_path, url = served_repo
    clone = make_clone(url, tmp_path, "busy")
    c1 = edit_commit(clone, ds_path, deletes=[1], message="c1")
    sleeps = []
    policy = RetryPolicy(attempts=2, base_delay=0.01, sleep=sleeps.append)
    with pytest.raises(HttpTransportError) as exc:
        raw_receive(url, clone, c1, old_oid="f" * 40, retry=policy)
    e = exc.value
    assert e.shed and e.transient and not e.terminal
    assert e.retry_after == 2.0
    assert sleeps == [2.0]  # retried once, floored by the server's pacing
    assert counter("server.rebase.exhausted") == 2
    # nothing landed, nothing left behind
    assert repo.refs.get("refs/heads/main") != c1
    quarantine = os.path.join(repo.odb.objects_dir, "quarantine")
    assert not os.path.isdir(quarantine) or os.listdir(quarantine) == []


def test_merge_queue_overflow_sheds_with_retry_after(
    served_repo, tmp_path, monkeypatch
):
    """KART_SERVE_MERGE_QUEUE bounds the per-ref line: with the only slot
    held, a push is shed busy (429 + Retry-After) instead of queueing; once
    the slot frees, the identical push lands."""
    monkeypatch.setenv("KART_SERVE_MERGE_QUEUE", "1")
    repo, ds_path, url = served_repo
    clone = make_clone(url, tmp_path, "q")
    c1 = edit_commit(clone, ds_path, deletes=[1], message="c1")
    queue = service.merge_queue_for(repo)
    slot = queue.slot("refs/heads/main")
    slot.__enter__()  # occupy the line like an in-flight contended push
    try:
        with pytest.raises(HttpTransportError) as exc:
            raw_receive(url, clone, c1, old_oid=None)
        assert exc.value.shed and not exc.value.terminal
        assert counter("server.merge_queue.shed") == 1
    finally:
        slot.__exit__(None, None, None)
    base = repo.refs.get("refs/heads/main")
    result = raw_receive(url, clone, c1, old_oid=base)
    assert result["updated"] == {"refs/heads/main": c1}


def test_merge_queue_orders_waiters_fifo():
    """Unit: tickets are served strictly in arrival order, the depth gauge
    drains, and a released line is reclaimed."""
    queue = service.MergeQueue()
    order = []
    first = queue.slot("refs/heads/x")
    first.__enter__()
    threads = []

    def waiter(i):
        with queue.slot("refs/heads/x"):
            order.append(i)

    for i in range(3):
        t = threading.Thread(target=waiter, args=(i,))
        t.start()
        threads.append(t)
        # let each enqueue before the next (arrival order = ticket order)
        import time as _time

        deadline = _time.monotonic() + 5
        while len(queue._lines["refs/heads/x"]) and (
            queue._lines["refs/heads/x"]["next"] < i + 2
        ):
            if _time.monotonic() > deadline:  # pragma: no cover - wedge guard
                raise AssertionError("waiter never enqueued")
            _time.sleep(0.005)
    first.__exit__(None, None, None)
    for t in threads:
        t.join(10)
    assert order == [0, 1, 2]
    assert queue._lines == {}  # line reclaimed once drained


# ---------------------------------------------------------------------------
# RetryPolicy terminal/paced split (per-verb units)
# ---------------------------------------------------------------------------


def test_retry_policy_terminal_beats_any_retryable_predicate():
    sleeps = []
    policy = RetryPolicy(attempts=5, base_delay=0.01, sleep=sleeps.append)
    calls = []

    def fn():
        calls.append(1)
        raise HttpTransportError(
            "conflicts, human required", transient=True, shed=True,
            terminal=True, conflict_report={"ref": "refs/heads/main"},
        )

    with pytest.raises(HttpTransportError) as exc:
        policy.call(fn, retryable=lambda e: True)
    assert len(calls) == 1 and sleeps == []
    assert exc.value.conflict_report["ref"] == "refs/heads/main"


def test_retry_policy_busy_is_paced_for_push_verbs():
    """The receive-pack retryable predicate (pre-write or shed) retries a
    busy rejection, honouring its Retry-After floor."""
    from kart_tpu.transport.retry import is_pre_write

    def retryable(exc):
        return is_pre_write(exc) or getattr(exc, "shed", False)

    sleeps = []
    policy = RetryPolicy(attempts=3, base_delay=0.01, sleep=sleeps.append)
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise HttpTransportError(
                "busy: CAS kept moving", transient=True, shed=True,
                retry_after=1.5,
            )
        return "landed"

    assert policy.call(fn, retryable=retryable) == "landed"
    assert sleeps == [1.5, 1.5]


def test_diverged_push_ships_only_new_objects(served_repo, tmp_path, monkeypatch):
    """With the client-side veto gone, a diverged push against a tip we
    never fetched must still ship only the NEW objects: the haves closure
    is seeded from our remote-tracking refs (the server provably holds
    them), not just from advertised tips our odb may lack."""
    repo, ds_path, url = served_repo
    w1 = make_clone(url, tmp_path, "ww1")
    w2 = make_clone(url, tmp_path, "ww2")
    edit_commit(w1, ds_path, deletes=[1], message="w1")
    transport.push(w1, "origin")  # tip is now unknown to w2
    edit_commit(w2, ds_path, deletes=[2], message="w2")
    total_objects = sum(1 for _ in w2.odb.iter_oids())
    sent = {}
    orig = HttpRemote.receive_pack

    def spy(self, objects, updates, **kw):
        result = orig(self, objects, updates, **kw)
        sent["count"] = getattr(objects, "object_count", None)
        return result

    monkeypatch.setattr(HttpRemote, "receive_pack", spy)
    transport.push(w2, "origin")  # lands via server rebase
    assert sent["count"] is not None
    # one commit + the handful of rewritten trees — never the whole repo
    assert sent["count"] < total_objects / 2, (
        f"diverged push re-uploaded {sent['count']}/{total_objects} objects"
    )


def test_retry_after_zero_rides_the_wire():
    """KART_SERVE_RETRY_AFTER=0 ('retry immediately') is a real value, not
    an absence: the wire fields and client attrs must carry it."""
    busy = Rejection("busy", "q", code="cas_busy", retry_after=0, shed=True)
    wire = rejection_wire_fields(busy)
    assert wire["retry_after"] == 0 and wire["shed"] is True
    attrs = error_attrs_from_wire({"error": "q", **wire})
    assert attrs["retry_after"] == 0 and attrs["shed"] is True


def test_rejection_wire_round_trip():
    """protocol.Rejection -> wire fields -> client error attrs survives the
    trip for both transports' error shapes."""
    rej = Rejection(
        "conflict", "merging would conflict", code="merge_conflict",
        ref="refs/heads/main", terminal=True,
        conflict_report={"conflicts_total": 3},
    )
    kind, msg = rej  # tuple compatibility
    assert (kind, msg) == ("conflict", "merging would conflict")
    wire = rejection_wire_fields(rej)
    assert wire["terminal"] is True
    assert wire["code"] == "merge_conflict"
    attrs = error_attrs_from_wire({"error": msg, **wire})
    assert attrs == {
        "terminal": True, "conflict_report": {"conflicts_total": 3},
    }
    busy = Rejection(
        "busy", "queue full", code="queue_full", retry_after=3, shed=True
    )
    attrs = error_attrs_from_wire({"error": "queue full",
                                   **rejection_wire_fields(busy)})
    assert attrs == {"retry_after": 3, "shed": True}
    assert error_attrs_from_wire(None) == {}
    assert error_attrs_from_wire({"error": "plain"}) == {}


# ---------------------------------------------------------------------------
# refname hygiene a server-constructed ref could trip (satellite)
# ---------------------------------------------------------------------------


def test_validate_rejects_nested_prefix_df_collisions(tmp_path):
    repo, _ = make_imported_repo(tmp_path, n=3)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    tip = repo.refs.get("refs/heads/main")
    repo.refs.set("refs/heads/a", tip)
    # file blocks directory: refs/heads/a exists, push refs/heads/a/b
    rej = service.validate_ref_updates(
        repo,
        {"updates": [{"ref": "refs/heads/a/b", "old": None, "new": tip}]},
    )
    assert rej is not None and rej.code == "df_conflict" and rej.terminal
    # directory blocks file: refs/heads/x/y exists, push refs/heads/x
    repo.refs.set("refs/heads/x/y", tip)
    rej = service.validate_ref_updates(
        repo,
        {"updates": [{"ref": "refs/heads/x", "old": None, "new": tip}]},
    )
    assert rej is not None and rej.code == "df_conflict"
    # deleting never D/F-conflicts; a plain update of the existing ref is fine
    assert service.validate_ref_updates(
        repo, {"updates": [{"ref": "refs/heads/a", "old": tip, "new": tip}]}
    ) is None


def test_validate_rejects_lock_debris_shaped_names(tmp_path):
    """A ref named like atomic-write crash debris (x.lock<pid>/x.tmp<pid>)
    would be invisible to iter_refs and swept by gc — refused at the wire
    (and by refs.set itself)."""
    from kart_tpu.core.refs import RefError, check_ref_format

    repo, _ = make_imported_repo(tmp_path, n=3)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    tip = repo.refs.get("refs/heads/main")
    for bad in (
        "refs/heads/main.lock123",
        "refs/heads/topic.tmp42",
        "refs/heads/nested/x.lock7",
        "refs/heads/feature.tmp",
    ):
        rej = service.validate_ref_updates(
            repo, {"updates": [{"ref": bad, "old": None, "new": tip}]}
        )
        assert rej is not None and rej[0] == "bad", bad
        with pytest.raises(RefError):
            check_ref_format(bad, require_refs_prefix=True)
    # near-misses stay legal
    check_ref_format("refs/heads/v1.0-tmp", require_refs_prefix=True)
    check_ref_format("refs/heads/lock123", require_refs_prefix=True)


def test_checked_out_branch_protected_under_concurrent_rebase(tmp_path):
    """deny_current outranks the rebase path: a stale push to the served
    repo's checked-out branch is refused terminally — the server must not
    'helpfully' rebase onto a branch whose working copy would desync."""
    import time

    repo, ds_path = make_imported_repo(tmp_path, n=6)
    # non-bare, denyCurrentBranch left at the refuse default
    server = make_server(repo)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/"
    try:
        telemetry.reset(disable=False)
        clone = transport.clone(url, tmp_path / "clone", do_checkout=False)
        clone.config.set_many({"user.name": "C", "user.email": "c@x"})
        c1 = edit_commit(clone, ds_path, deletes=[1], message="c1")
        with pytest.raises(HttpTransportError) as exc:
            # stale CAS base: without the deny guard this would rebase
            raw_receive(url, clone, c1, old_oid="0" * 40)
        assert exc.value.terminal
        assert "checked-out branch" in str(exc.value)
        assert counter("server.rebase.attempts") == 0
        time.sleep(0)  # (scheduling fairness; keeps flake detectors honest)
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# columnar conflict-summary fast path (satellite: merge/index.py)
# ---------------------------------------------------------------------------


def test_summary_counts_fast_path_matches_label_loop(tmp_path):
    """ColumnarConflicts.summary_counts (the PkLabels O(1) lane) and the
    generic label loop must summarise identically — the server report and
    `kart merge` output both ride _conflict_summary."""
    from kart_tpu.cli.merge_cmds import _conflict_summary
    from kart_tpu.merge import do_merge

    repo, ds_path = make_imported_repo(tmp_path, n=8)
    tip = repo.refs.get("refs/heads/main")
    edit_commit(
        repo, ds_path,
        updates=[
            {"fid": 2, "geom": None, "name": "ours-2", "rating": 1.0},
            {"fid": 3, "geom": None, "name": "ours-3", "rating": 1.0},
        ],
        message="ours",
    )
    repo.refs.set("refs/heads/theirs", tip)
    edit_commit(
        repo, ds_path,
        updates=[
            {"fid": 2, "geom": None, "name": "theirs-2", "rating": 2.0},
            {"fid": 3, "geom": None, "name": "theirs-3", "rating": 2.0},
        ],
        message="theirs",
        ref="refs/heads/theirs",
    )
    result = do_merge(repo, "refs/heads/theirs", dry_run=True)
    conflicts = result.merge_index.conflicts
    fast = _conflict_summary(conflicts)
    # the generic fallback: strip the fast path and recompute
    slow = {}
    from kart_tpu.cli.merge_cmds import (
        _CONFLICT_PLACEHOLDER,
        _set_value_at_path,
        _summarise_tree,
    )

    for label in conflicts:
        _set_value_at_path(
            slow, tuple(label.split(":", 2)), _CONFLICT_PLACEHOLDER
        )
    slow = _summarise_tree(slow, 2)
    assert fast == slow == {ds_path: {"feature": 2}}
    counts = conflicts.summary_counts()
    assert counts == {(ds_path, "feature"): 2}
