"""Stable PK generation for PK-less sources (reference: kart/pk_generation.py
+ the PK-matching benchmark in tests/test_structure.py:762-784)."""

import numpy as np

from kart_tpu.importer.pk_generation import (
    PkGeneratingImportSource,
    assign_pks,
    GENERATED_PKS_ITEM,
)

COLS = ["name", "rating"]


def _features(*rows):
    return [dict(zip(COLS, r)) for r in rows]


class TestAssignPks:
    def test_fresh_assignment(self):
        feats = _features(("a", 1.0), ("b", 2.0), ("c", 3.0))
        pks, state = assign_pks(feats, COLS, None)
        assert list(pks) == [1, 2, 3]
        assert state["next"] == 4

    def test_reimport_identical_is_stable(self):
        feats = _features(("a", 1.0), ("b", 2.0))
        _, state = assign_pks(feats, COLS, None)
        # same content, re-ordered: PKs follow the content
        pks2, _ = assign_pks(_features(("b", 2.0), ("a", 1.0)), COLS, state)
        assert list(pks2) == [2, 1]

    def test_edited_feature_keeps_pk_by_similarity(self):
        feats = _features(("alpha", 1.0), ("beta", 2.0), ("gamma", 3.0))
        _, state = assign_pks(feats, COLS, None)
        # 'beta' renamed but rating unchanged: 1/2 columns match -> re-match
        edited = _features(("alpha", 1.0), ("beta-renamed", 2.0), ("gamma", 3.0))
        pks2, _ = assign_pks(edited, COLS, state)
        assert list(pks2) == [1, 2, 3]

    def test_new_feature_gets_new_pk(self):
        feats = _features(("a", 1.0))
        _, state = assign_pks(feats, COLS, None)
        pks2, state2 = assign_pks(
            _features(("a", 1.0), ("z", 99.0)), COLS, state
        )
        assert list(pks2) == [1, 2]
        assert state2["next"] == 3

    def test_deleted_feature_pk_not_reused(self):
        feats = _features(("a", 1.0), ("b", 2.0))
        _, state = assign_pks(feats, COLS, None)
        # 'b' (totally different content) deleted; new unrelated feature must
        # NOT inherit pk 2 (no column matches => below threshold)
        pks2, _ = assign_pks(
            _features(("a", 1.0), ("completely-new", 77.0)), COLS, state
        )
        assert pks2[0] == 1
        assert pks2[1] == 3

    def test_duplicate_content_rows(self):
        feats = _features(("dup", 1.0), ("dup", 1.0))
        pks, _ = assign_pks(feats, COLS, None)
        assert sorted(pks) == [1, 2]  # both get PKs, no collision


class TestCsvImportRoundtrip:
    def _write_csv(self, path, rows):
        with open(path, "w") as f:
            f.write("name,rating\n")
            for r in rows:
                f.write(f"{r[0]},{r[1]}\n")

    def test_import_and_stable_reimport(self, tmp_path):
        from kart_tpu.core.repo import KartRepo
        from kart_tpu.importer import ImportSource
        from kart_tpu.importer.importer import import_sources

        csv_path = tmp_path / "records.csv"
        self._write_csv(csv_path, [("a", 1.5), ("b", 2.5), ("c", 3.5)])
        repo = KartRepo.init_repository(tmp_path / "repo")
        repo.config.set_many({"user.name": "T", "user.email": "t@x"})
        import_sources(repo, ImportSource.open(str(csv_path)))

        ds = repo.datasets("HEAD")["records"]
        assert ds.schema.pk_columns[0].name == "auto_pk"
        assert ds.feature_count == 3
        f1 = ds.get_feature([1])
        assert f1["name"] == "a"
        # state persisted in the dataset
        assert ds.get_meta_item(GENERATED_PKS_ITEM) is not None

        # re-import with one edit: unchanged rows keep PKs
        self._write_csv(csv_path, [("c", 3.5), ("a", 1.5), ("b", 9.9)])
        import_sources(
            repo, ImportSource.open(str(csv_path)), replace_existing=True
        )
        ds2 = repo.datasets("HEAD")["records"]
        assert ds2.get_feature([1])["name"] == "a"
        assert ds2.get_feature([3])["name"] == "c"
        # 'b' edited its rating only -> similarity keeps pk 2
        assert ds2.get_feature([2])["name"] == "b"
        assert ds2.get_feature([2])["rating"] == 9.9


def test_wrap_if_needed_passthrough():
    class FakeSource:
        class schema:
            pk_columns = ("something",)

    src = FakeSource()
    assert PkGeneratingImportSource.wrap_if_needed(src, None) is src


def test_duplicate_content_stable_across_reimports():
    """Duplicate rows keep their PKs on every re-import (PK lists per hash)."""
    feats = _features(("dup", 1.0), ("dup", 1.0), ("x", 2.0))
    pks1, state1 = assign_pks(feats, COLS, None)
    pks2, state2 = assign_pks(feats, COLS, state1)
    pks3, _ = assign_pks(feats, COLS, state2)
    assert list(pks1) == list(pks2) == list(pks3)
