"""kart query (ISSUE 16): predicate-pushdown scans, the device-parallel
cross-commit spatial join, the commit-addressed result cache, and the
fleet scatter.

The parity claims these tests pin down: a bbox scan equals the brute-force
numpy envelope test; a spatial join equals the O(n*m) per-row reference
(including anti-meridian wraps, polar boxes and NULL-geometry NaN rows);
``sharded_jax`` join counts are bit-identical to ``host_native`` on the
8-device virtual mesh; block-range partials sum exactly to the full join;
and a scattered two-node query merges to the same document a single node
computes."""

import json
import threading
import urllib.error
import urllib.request
from urllib.parse import quote

import numpy as np
import pytest

from kart_tpu import telemetry
from kart_tpu.diff import sidecar
from kart_tpu.models.schema import ColumnSchema, Schema
from kart_tpu.ops.bbox import bbox_intersects_np
from kart_tpu.query import QueryError, run_query
from kart_tpu.query.scan import compile_where, parse_bbox
from kart_tpu.synth import synth_repo
from kart_tpu.transport.http import make_server

pytestmark = pytest.mark.query

PK0 = 1 << 24  # synth pk base


@pytest.fixture(scope="module")
def spatial(tmp_path_factory):
    """A two-commit spatial synth repo: 9000 rows (3 sidecar blocks),
    envelope columns present, feature blobs only for the edited rows."""
    repo, info = synth_repo(
        str(tmp_path_factory.mktemp("query") / "spatial"),
        9000,
        spatial=True,
        blobs="changed",
    )
    return repo, info


@pytest.fixture(scope="module")
def attr(tmp_path_factory):
    """A two-commit non-spatial synth repo with every blob real — the
    stage-3 (blob-backed value predicate) route needs readable blobs."""
    repo, info = synth_repo(
        str(tmp_path_factory.mktemp("query") / "attr"), 300, blobs="real"
    )
    return repo, info


def envelopes_of(repo, commit, ds_path="synth"):
    ds = repo.datasets(commit)[ds_path]
    block = sidecar.ensure_block(repo, ds, pad=False)
    return np.asarray(block.envelopes, dtype=np.float64), np.asarray(
        block.keys
    )


def selective_bbox(env, frac=0.1):
    """A W,S,E,N string covering roughly the first ``frac`` of the
    longitude span — selective enough to prune whole blocks."""
    w = float(env[:, 0].min())
    e = w + (float(env[:, 2].max()) - w) * frac
    return f"{w},{float(env[:, 1].min())},{e},{float(env[:, 3].max())}"


def get_json(url, path):
    """GET -> (status, parsed body or raw bytes, headers)."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + path, timeout=30) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# ---------------------------------------------------------------------------
# the predicate grammar
# ---------------------------------------------------------------------------


def _text_schema():
    return Schema(
        [
            ColumnSchema(
                id="a1b2c3d4-0001-4000-8000-000000000001",
                name="fid",
                data_type="integer",
                pk_index=0,
                extra_type_info={"size": 64},
            ),
            ColumnSchema(
                id="a1b2c3d4-0005-4000-8000-000000000005",
                name="name",
                data_type="text",
                pk_index=None,
            ),
        ]
    )


class TestGrammar:
    def test_parse_bbox_accepts_antimeridian_wrap(self):
        box = parse_bbox("170,-50,-170,-40")
        assert list(box) == [170.0, -50.0, -170.0, -40.0]

    @pytest.mark.parametrize(
        "text", ["nope", "1,2,3", "1,2,3,4,5", "0,10,0,-10", "0,0,0,inf"]
    )
    def test_parse_bbox_rejects(self, text):
        with pytest.raises(QueryError):
            parse_bbox(text)

    def test_compile_where_typed_forms(self, spatial):
        repo, info = spatial
        schema = repo.datasets(info["base_commit"])["synth"].schema
        preds = compile_where(
            "fid >= 5 AND rating < 2.5 AND rating IS NOT NULL", schema
        )
        assert [p.kind for p in preds] == ["cmp", "cmp", "notnull"]
        assert [p.on_pk for p in preds] == [True, False, False]
        assert preds[0].value == 5 and isinstance(preds[0].value, int)
        assert preds[1].value == 2.5

        (p,) = compile_where("fid IN (1, 2, 3)", schema)
        assert p.kind == "in" and p.values == {1, 2, 3} and p.on_pk

    @pytest.mark.parametrize(
        "where",
        [
            "nosuch = 1",  # unknown column
            "fid = 1.5",  # float literal for an integer column
            "fid = 'x'",  # string literal for an integer column
            "geom = 1",  # geometry column: --bbox territory
            "fid = 1 rating = 2",  # missing AND
            "fid = 1 AND",  # dangling AND
            "rating >",  # missing literal
            "fid IN (1",  # unclosed IN
            "rating IS 3",  # IS without NULL
        ],
    )
    def test_compile_where_rejects(self, spatial, where):
        repo, info = spatial
        schema = repo.datasets(info["base_commit"])["synth"].schema
        with pytest.raises(QueryError):
            compile_where(where, schema)

    def test_text_literals_need_quotes(self):
        schema = _text_schema()
        (p,) = compile_where("name = 'it''s'", schema)
        assert p.value == "it's"
        with pytest.raises(QueryError):
            compile_where("name = bare", schema)


# ---------------------------------------------------------------------------
# the pushdown scan
# ---------------------------------------------------------------------------


class TestScan:
    def test_bbox_count_matches_bruteforce(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        env, _keys = envelopes_of(repo, base)
        bbox = selective_bbox(env)
        expected = int(
            np.count_nonzero(bbox_intersects_np(env, parse_bbox(bbox)))
        )
        assert 0 < expected < len(env)
        doc = run_query(repo, base, "synth", bbox=bbox)
        assert doc["count"] == expected
        assert doc["kind"] == "scan" and doc["commit"] == base

    def test_selective_bbox_prunes_blocks(self, spatial, monkeypatch):
        repo, info = spatial
        base = info["base_commit"]
        env, _keys = envelopes_of(repo, base)
        bbox = selective_bbox(env, frac=0.05)
        doc = run_query(repo, base, "synth", bbox=bbox)
        assert doc["stats"]["blocks"] == 3  # 9000 rows / 4096-row blocks
        assert doc["stats"]["blocks_pruned"] >= 1
        # prune forced off: bit-identical result, no blocks skipped
        monkeypatch.setenv("KART_BLOCK_PRUNE", "0")
        unpruned = run_query(repo, base, "synth", bbox=bbox)
        assert unpruned["count"] == doc["count"]
        assert unpruned["stats"]["blocks_pruned"] == 0

    def test_pk_predicates_vectorized(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        doc = run_query(repo, base, "synth", where=f"fid < {PK0 + 100}")
        assert doc["count"] == 100
        doc = run_query(
            repo,
            base,
            "synth",
            where=f"fid IN ({PK0}, {PK0 + 7}, {PK0 + 9000})",
        )
        assert doc["count"] == 2  # PK0+9000 is past the end

    def test_bbox_and_pk_combined(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        env, keys = envelopes_of(repo, base)
        bbox = selective_bbox(env)
        hits = bbox_intersects_np(env, parse_bbox(bbox))
        cut = PK0 + 4000
        expected = int(np.count_nonzero(hits & (keys < cut)))
        doc = run_query(
            repo, base, "synth", where=f"fid < {cut}", bbox=bbox
        )
        assert doc["count"] == expected

    def test_blob_backed_value_predicates(self, attr):
        repo, info = attr
        base, n = info["base_commit"], info["n"]
        # base-commit rating is pk/2.0 for every row
        cut = (PK0 + 40) / 2.0
        doc = run_query(repo, base, "synth", where=f"rating < {cut}")
        assert doc["count"] == 40
        assert doc["stats"]["rows_decoded"] == n  # no pk prefilter: all decode
        doc = run_query(repo, base, "synth", where="rating IS NOT NULL")
        assert doc["count"] == n
        # the pk stage shrinks what the blob stage decodes
        doc = run_query(
            repo,
            base,
            "synth",
            where=f"fid < {PK0 + 50} AND rating >= {PK0 / 2.0}",
        )
        assert doc["count"] == 50 and doc["stats"]["rows_decoded"] == 50

    def test_json_output_pages(self, attr):
        repo, info = attr
        base = info["base_commit"]
        where = f"fid < {PK0 + 10}"
        seen = []
        page = 0
        while page is not None:
            doc = run_query(
                repo,
                base,
                "synth",
                where=where,
                output="json",
                page=page,
                page_size=4,
            )
            assert doc["page_size"] == 4
            seen.extend(f["fid"] for f in doc["features"])
            page = doc["next_page"]
        assert seen == list(range(PK0, PK0 + 10))
        # and every feature carries its real attribute values
        doc = run_query(
            repo, base, "synth", where=f"fid = {PK0 + 4}", output="json"
        )
        assert doc["features"] == [
            {"fid": PK0 + 4, "rating": (PK0 + 4) / 2.0}
        ]

    def test_count_by_pk_groups(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        doc = run_query(
            repo, base, "synth", where=f"fid < {PK0 + 3}", count_by="fid"
        )
        assert doc["groups"] == {
            str(PK0): 1,
            str(PK0 + 1): 1,
            str(PK0 + 2): 1,
        }

    def test_bbox_union_covers_selection(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        env, _keys = envelopes_of(repo, base)
        bbox = selective_bbox(env)
        doc = run_query(repo, base, "synth", bbox=bbox, output="bbox")
        w, s, e, n = doc["bbox_union"]
        sel = env[bbox_intersects_np(env, parse_bbox(bbox))]
        assert w <= sel[:, 0].min() and e >= sel[:, 2].max()
        assert s <= sel[:, 1].min() and n >= sel[:, 3].max()

    def test_scan_is_deterministic_bytes(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        env, _keys = envelopes_of(repo, base)
        bbox = selective_bbox(env)
        a = run_query(repo, base, "synth", bbox=bbox, output="count")
        b = run_query(repo, base, "synth", bbox=bbox, output="count")
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)

    def test_scan_surface_errors(self, spatial):
        repo, info = spatial
        base = info["base_commit"]
        with pytest.raises(QueryError):  # partials are a join-only concept
            run_query(repo, base, "synth", part=(0, 10))
        with pytest.raises(QueryError):  # join and --where don't combine
            run_query(
                repo,
                base,
                "synth",
                where="fid = 1",
                intersects=(base, "synth"),
            )
        with pytest.raises(QueryError):
            run_query(repo, base, "synth", output="nosuch")
        with pytest.raises(QueryError):
            run_query(repo, "no-such-ref", "synth")
        with pytest.raises(QueryError):
            run_query(repo, base, "no-such-dataset")


# ---------------------------------------------------------------------------
# the spatial join
# ---------------------------------------------------------------------------


def brute_join(build_env, probe_env):
    """The O(n*m) reference: per probe row, the numpy envelope test against
    every build row (an implementation independent of the join kernel)."""
    counts = np.zeros(len(probe_env), dtype=np.int64)
    for i in range(len(probe_env)):
        q = probe_env[i].astype(np.float64)
        if not np.isfinite(q).all():
            continue  # NULL geometry: matches nothing
        counts[i] = np.count_nonzero(bbox_intersects_np(build_env, q))
    return counts


class _ProbeStub:
    """The minimal probe-block shape join_counts_for_range needs — lets the
    wrap/polar/NaN matrix run on hand-built envelope columns."""

    def __init__(self, env):
        self.envelopes = np.asarray(env, dtype=np.float32)
        self.env_blocks = None
        self.count = len(env)


class TestJoin:
    def test_time_travel_join_matches_bruteforce(self, spatial):
        repo, info = spatial
        base, edit = info["base_commit"], info["edit_commit"]
        build_env, _ = envelopes_of(repo, edit)
        probe_env, _ = envelopes_of(repo, base)
        ref = brute_join(build_env, probe_env)
        doc = run_query(
            repo, base, "synth", intersects=(edit, "synth"), allow_device=False
        )
        assert doc["pairs"] == int(ref.sum())
        assert doc["count"] == int(np.count_nonzero(ref))
        assert doc["stats"]["build_rows"] == len(build_env)
        assert doc["stats"]["probe_rows"] == len(probe_env)
        assert doc["stats"]["tiles"] >= 2  # 9000 build rows / 4096-row tiles

    def test_join_parts_sum_to_whole(self, spatial):
        repo, info = spatial
        base, edit = info["base_commit"], info["edit_commit"]
        full = run_query(repo, base, "synth", intersects=(edit, "synth"))
        parts = [
            run_query(
                repo, base, "synth", intersects=(edit, "synth"), part=p
            )
            for p in ((0, 4096), (4096, 9000))
        ]
        assert sum(p["pairs"] for p in parts) == full["pairs"]
        assert sum(p["count"] for p in parts) == full["count"]
        # a partial still reports the *full* probe side in its stats
        assert all(p["stats"]["probe_rows"] == 9000 for p in parts)
        with pytest.raises(QueryError):  # out-of-range partial
            run_query(
                repo, base, "synth", intersects=(edit, "synth"), part=(0, 9001)
            )

    def test_join_bbox_restricts_both_sides(self, spatial):
        repo, info = spatial
        base, edit = info["base_commit"], info["edit_commit"]
        build_env, _ = envelopes_of(repo, edit)
        probe_env, _ = envelopes_of(repo, base)
        bbox = selective_bbox(probe_env, frac=0.2)
        q = parse_bbox(bbox)
        b_sel = build_env[bbox_intersects_np(build_env, q)]
        p_hits = bbox_intersects_np(probe_env, q)
        ref = brute_join(b_sel, probe_env)
        ref[~p_hits] = 0
        doc = run_query(
            repo, base, "synth", intersects=(edit, "synth"), bbox=bbox
        )
        assert doc["pairs"] == int(ref.sum())
        assert doc["count"] == int(np.count_nonzero(ref))

    def test_join_json_reports_match_counts(self, spatial):
        repo, info = spatial
        base, edit = info["base_commit"], info["edit_commit"]
        build_env, _ = envelopes_of(repo, edit)
        probe_env, keys = envelopes_of(repo, base)
        ref = brute_join(build_env, probe_env)
        doc = run_query(
            repo,
            base,
            "synth",
            intersects=(edit, "synth"),
            output="json",
            page_size=50,
        )
        assert doc["page"] == 0 and len(doc["matches"]) == 50
        nz = np.flatnonzero(ref)
        for got, i in zip(doc["matches"], nz[:50].tolist()):
            assert got["pk"] == int(keys[i])
            assert got["matches"] == int(ref[i])
        assert doc["next_page"] == (1 if len(nz) > 50 else None)

    def test_wrap_polar_and_nan_rows(self):
        """The crafted matrix: anti-meridian wraps on either side, polar
        boxes, and NaN (NULL-geometry) rows on either side — the staged
        join equals the brute-force reference on all of them."""
        from kart_tpu.query.join import join_counts_for_range

        rng = np.random.default_rng(7)
        def mk(n):
            w = rng.uniform(-179, 178, n)
            s = rng.uniform(-89, 88, n)
            env = np.stack(
                [w, s, w + rng.uniform(0.1, 2, n), s + rng.uniform(0.1, 2, n)],
                axis=1,
            ).astype(np.float32)
            env[:: n // 5] = [[170.0, -10.0, -170.0, 10.0]]  # wrapped
            env[1 :: n // 5] = [[-60.0, 85.0, 60.0, 90.0]]  # polar
            env[2 :: n // 5] = [[np.nan] * 4]  # NULL geometry
            return env

        build, probe = mk(600), mk(500)
        ref = brute_join(build, probe)
        counts, total = join_counts_for_range(
            build, _ProbeStub(probe), 0, len(probe), allow_device=False
        )
        assert np.array_equal(counts, ref)
        assert total == int(ref.sum())
        # wrapped probe against wrapped build always overlaps in longitude
        assert counts[0] > 0
        # NaN rows never match, in either role
        assert counts[2] == 0

    def test_sharded_join_bit_identical_to_host(self):
        from kart_tpu.diff.backend import (
            _host_join_counts,
            sharded_join_counts,
        )

        rng = np.random.default_rng(11)
        w = rng.uniform(-179, 178, 3000)
        s = rng.uniform(-89, 88, 3000)
        probe = np.stack([w, s, w + 1, s + 1], axis=1).astype(np.float32)
        probe[::97] = [[175.0, -5.0, -175.0, 5.0]]
        probe[::131] = [[np.nan] * 4]
        build = probe[:700][::-1].copy()
        hc, ht = _host_join_counts(build, probe)
        sc, st = sharded_join_counts(build, probe)
        assert np.array_equal(hc, sc)  # bit-identical on the 8-device mesh
        assert ht == st

    def test_device_route_matches_host(self, spatial, monkeypatch):
        repo, info = spatial
        base, edit = info["base_commit"], info["edit_commit"]
        host = run_query(
            repo, base, "synth", intersects=(edit, "synth"), allow_device=False
        )
        monkeypatch.setenv("KART_DIFF_SHARDED", "1")
        dev = run_query(repo, base, "synth", intersects=(edit, "synth"))
        assert (dev["pairs"], dev["count"]) == (host["pairs"], host["count"])

    def test_pack_env_round_roundtrip(self):
        from kart_tpu.diff.device_batch import pack_env_round

        env = np.arange(40, dtype=np.float32).reshape(10, 4)
        lo, hi = 2, 9
        cols = pack_env_round(env, lo, hi, n_shards=4, per=2)
        assert len(cols) == 4 and cols[0].shape == (4, 2)
        for c, col in enumerate(cols):
            flat = col.reshape(-1)
            assert np.array_equal(flat[: hi - lo], env[lo:hi, c])
            assert np.isnan(flat[hi - lo :]).all()  # padding never matches
        with pytest.raises(ValueError):
            pack_env_round(env, 0, 10, n_shards=2, per=2)


# ---------------------------------------------------------------------------
# GET /api/v1/query: the cached, ETagged serving lane
# ---------------------------------------------------------------------------


@pytest.fixture()
def served_spatial(spatial):
    repo, info = spatial
    from kart_tpu.query import cache as qcache

    with qcache._query_caches_lock:
        qcache._QUERY_CACHES.clear()
    telemetry.reset(disable=False)
    server = make_server(repo)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield repo, info, url
    server.shutdown()
    server.server_close()
    telemetry.reset()


def _counter(name, **labels):
    for n, l, v in telemetry.snapshot()["counters"]:
        if n == name and l == labels:
            return v
    return 0


class TestHttpQuery:
    def test_scan_etag_revalidation_and_cache(self, served_spatial):
        repo, info, url = served_spatial
        base = info["base_commit"]
        env, _ = envelopes_of(repo, base)
        bbox = quote(selective_bbox(env), safe="")
        path = f"/api/v1/query?ref={base}&dataset=synth&bbox={bbox}"
        status, body, headers = get_json(url, path)
        assert status == 200
        etag = headers["ETag"]
        assert etag.startswith('"') and "immutable" in headers["Cache-Control"]
        doc = json.loads(body)
        assert doc["kind"] == "scan" and doc["count"] > 0

        req = urllib.request.Request(
            url + path, headers={"If-None-Match": etag}
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 304

        # an unconditional repeat serves the cached bytes
        status, again, headers2 = get_json(url, path)
        assert status == 200 and again == body and headers2["ETag"] == etag
        assert _counter("query.cache.hits") >= 1

    def test_join_and_partials_over_http(self, served_spatial):
        repo, info, url = served_spatial
        base, edit = info["base_commit"], info["edit_commit"]
        local = run_query(repo, base, "synth", intersects=(edit, "synth"))
        path = (
            f"/api/v1/query?ref={base}&dataset=synth&intersects={edit}:synth"
        )
        status, body, _ = get_json(url, path)
        assert status == 200
        doc = json.loads(body)
        assert doc["pairs"] == local["pairs"]

        totals = []
        for part in ("0:4096", "4096:9000"):
            status, body, headers = get_json(url, f"{path}&part={part}")
            assert status == 200
            pdoc = json.loads(body)
            assert pdoc["part"] == [int(p) for p in part.split(":")]
            assert headers["ETag"]  # partials are peer-cacheable payloads
            totals.append(pdoc["pairs"])
        assert sum(totals) == local["pairs"]

    def test_join_json_pagination_over_http(self, served_spatial):
        repo, info, url = served_spatial
        base, edit = info["base_commit"], info["edit_commit"]
        path = (
            f"/api/v1/query?ref={base}&dataset=synth&intersects={edit}:synth"
            f"&output=json&page_size=10"
        )
        status, body, _ = get_json(url, path + "&page=0")
        assert status == 200
        p0 = json.loads(body)
        assert len(p0["matches"]) == 10 and p0["next_page"] == 1
        status, body, _ = get_json(url, path + "&page=1")
        p1 = json.loads(body)
        assert p1["page"] == 1
        assert p0["matches"][-1]["pk"] < p1["matches"][0]["pk"]

    @pytest.mark.parametrize(
        "path",
        [
            "/api/v1/query?dataset=synth",  # no ref
            "/api/v1/query?ref=HEAD",  # no dataset
            "/api/v1/query?ref=HEAD&dataset=synth&where=nosuch%20%3D%201",
            "/api/v1/query?ref=HEAD&dataset=synth&bbox=nope",
            "/api/v1/query?ref=HEAD&dataset=synth&part=xx",
            "/api/v1/query?ref=HEAD&dataset=nosuch",
            "/api/v1/query?ref=HEAD&dataset=synth&page=abc",
        ],
    )
    def test_bad_requests_are_400(self, served_spatial, path):
        _repo, _info, url = served_spatial
        status, body, _ = get_json(url, path)
        assert status == 400
        assert "error" in json.loads(body)

    def test_stats_document_gains_query_block(self, served_spatial):
        repo, info, url = served_spatial
        base = info["base_commit"]
        get_json(
            url, f"/api/v1/query?ref={base}&dataset=synth&where=fid%20%3C%20{PK0 + 5}"
        )
        status, body, _ = get_json(url, "/api/v1/stats?format=json")
        assert status == 200
        payload = json.loads(body)
        q = payload["query"]
        assert q["scans"] >= 1 and "pairs_emitted" in q

    def test_top_renders_query_line(self):
        from kart_tpu.cli.top_cmds import render_top

        frame = render_top(
            {
                "snapshot": {},
                "rates": {},
                "query": {
                    "scans": 3,
                    "joins": 1,
                    "blocks_pruned": 5,
                    "pairs_emitted": 42,
                    "scatter_parts": 2,
                    "cache_hits": 1,
                    "cache_misses": 2,
                },
            },
            "http://x",
        )
        assert "query  scans 3" in frame
        assert "pairs 42" in frame and "cache 1h/2m" in frame


# ---------------------------------------------------------------------------
# the fleet scatter
# ---------------------------------------------------------------------------


@pytest.fixture()
def _scatter_state(monkeypatch):
    from kart_tpu.fleet import peercache
    from kart_tpu.query import cache as qcache

    telemetry.reset(disable=False)
    for var in ("KART_FAULTS", "KART_PEER_CACHE", "KART_QUERY_SCATTER"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("KART_TRANSPORT_RETRY_BASE", "0.01")
    monkeypatch.setenv("KART_TRANSPORT_RETRY_CAP", "0.05")
    with peercache._peer_caches_lock:
        peercache._PEER_CACHES.clear()
    with peercache._peer_down_lock:
        peercache._peer_down.clear()
    with qcache._query_caches_lock:
        qcache._QUERY_CACHES.clear()
    yield
    telemetry.reset()


def _serve(repo, fleet=None):
    server = make_server(repo, fleet=fleet)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


@pytest.fixture()
def scatter_pair(tmp_path, _scatter_state):
    """Two nodes over one shared store (the shared-storage fleet shape):
    node A scatters probe ranges, node B answers partials."""
    from kart_tpu import fleet as fleet_mod

    repo, info = synth_repo(
        str(tmp_path / "r"), 9000, spatial=True, blobs="changed"
    )
    server_b, url_b = _serve(repo)
    node = fleet_mod.FleetNode(repo, primary_url=None, peers=(url_b,))
    server_a, url_a = _serve(repo, fleet=node)
    yield repo, info, url_a, url_b
    for s in (server_a, server_b):
        s.shutdown()
        s.server_close()


class TestScatter:
    def test_scattered_join_merges_exact(self, scatter_pair):
        repo, info, url_a, _url_b = scatter_pair
        base, edit = info["base_commit"], info["edit_commit"]
        local = run_query(repo, base, "synth", intersects=(edit, "synth"))
        path = (
            f"/api/v1/query?ref={base}&dataset=synth&intersects={edit}:synth"
        )
        status, body, headers = get_json(url_a, path)
        assert status == 200
        doc = json.loads(body)
        assert doc["stats"]["scatter_parts"] == 2
        assert doc["pairs"] == local["pairs"]
        assert doc["count"] == local["count"]
        assert doc["part"] is None  # the merged doc is the full answer
        # part 1 really crossed the wire to the peer
        assert _counter("fleet.peer_cache.fetches") >= 1
        assert _counter("query.scatter_requests") == 1
        assert _counter("query.scatter_parts") == 2

        # the merged doc was published under the full key: a repeat is a
        # local cache hit serving the identical bytes, no new scatter
        status, again, _ = get_json(url_a, path)
        assert status == 200 and again == body
        assert _counter("query.scatter_requests") == 1

    def test_scatter_with_bbox_merges_exact(self, scatter_pair):
        repo, info, url_a, _url_b = scatter_pair
        base, edit = info["base_commit"], info["edit_commit"]
        env, _ = envelopes_of(repo, base)
        bbox = selective_bbox(env, frac=0.3)
        local = run_query(
            repo, base, "synth", intersects=(edit, "synth"), bbox=bbox
        )
        path = (
            f"/api/v1/query?ref={base}&dataset=synth&intersects={edit}:synth"
            f"&bbox={quote(bbox, safe='')}"
        )
        status, body, _ = get_json(url_a, path)
        assert status == 200
        doc = json.loads(body)
        assert doc["stats"]["scatter_parts"] == 2
        assert (doc["pairs"], doc["count"]) == (local["pairs"], local["count"])

    def test_scatter_disabled_by_env(self, scatter_pair, monkeypatch):
        repo, info, url_a, _url_b = scatter_pair
        base, edit = info["base_commit"], info["edit_commit"]
        monkeypatch.setenv("KART_QUERY_SCATTER", "0")
        path = (
            f"/api/v1/query?ref={base}&dataset=synth&intersects={edit}:synth"
        )
        status, body, _ = get_json(url_a, path)
        assert status == 200
        doc = json.loads(body)
        assert "scatter_parts" not in doc["stats"]
        assert _counter("query.scatter_requests") == 0

    def test_dead_peer_part_computed_locally(self, tmp_path, _scatter_state):
        from kart_tpu import fleet as fleet_mod

        repo, info = synth_repo(
            str(tmp_path / "r"), 9000, spatial=True, blobs="changed"
        )
        node = fleet_mod.FleetNode(
            repo, primary_url=None, peers=("http://127.0.0.1:9/",)
        )
        server, url = _serve(repo, fleet=node)
        try:
            base, edit = info["base_commit"], info["edit_commit"]
            local = run_query(repo, base, "synth", intersects=(edit, "synth"))
            status, body, _ = get_json(
                url,
                f"/api/v1/query?ref={base}&dataset=synth"
                f"&intersects={edit}:synth",
            )
            assert status == 200
            doc = json.loads(body)
            # the scatter degraded, the answer didn't
            assert doc["stats"]["scatter_parts"] == 2
            assert doc["pairs"] == local["pairs"]
        finally:
            server.shutdown()
            server.server_close()


# ---------------------------------------------------------------------------
# the result cache
# ---------------------------------------------------------------------------


class TestQueryCache:
    def test_key_covers_every_result_shaping_field(self):
        from kart_tpu.query.cache import etag_for, query_request_key

        base = query_request_key("c1" * 20, "ds")
        variants = [
            query_request_key("c2" * 20, "ds"),
            query_request_key("c1" * 20, "other"),
            query_request_key("c1" * 20, "ds", where="fid = 1"),
            query_request_key("c1" * 20, "ds", bbox="0,0,1,1"),
            query_request_key("c1" * 20, "ds", commit_oid2="c2" * 20),
            query_request_key("c1" * 20, "ds", ds_path2="ds2"),
            query_request_key("c1" * 20, "ds", output="json"),
            query_request_key("c1" * 20, "ds", count_by="fid"),
            query_request_key("c1" * 20, "ds", page=1),
            query_request_key("c1" * 20, "ds", page_size=10),
            query_request_key("c1" * 20, "ds", part="0:10"),
        ]
        assert len({base, *variants}) == len(variants) + 1
        assert etag_for(base) == f'"{base[:32]}"'

    def test_fill_publish_hit_and_crash_abandon(self):
        from kart_tpu.query.cache import QueryCache, query_filled

        cache = QueryCache(1 << 20)
        calls = []

        def compute():
            calls.append(1)
            return b"doc"

        assert query_filled(cache, "k", compute) == b"doc"
        assert query_filled(cache, "k", compute) == b"doc"
        assert len(calls) == 1  # second call was a memo hit

        def boom():
            raise RuntimeError("mid-fill crash")

        with pytest.raises(RuntimeError):
            query_filled(cache, "k2", boom)
        assert cache.stats()["entries"] == 1  # nothing published for k2
        assert query_filled(cache, "k2", compute) == b"doc"  # clean retry

    def test_filled_without_cache_computes(self):
        from kart_tpu.query.cache import query_filled

        assert query_filled(None, "k", lambda: b"x") == b"x"

    def test_budget_env_and_invalidation(self, tmp_path, monkeypatch):
        from kart_tpu.core.repo import KartRepo
        from kart_tpu.query.cache import (
            invalidate_query_caches,
            query_cache_for,
            query_filled,
        )

        repo = KartRepo.init_repository(str(tmp_path / "r"))
        monkeypatch.setenv("KART_QUERY_CACHE", "0")
        assert query_cache_for(repo) is None
        monkeypatch.setenv("KART_QUERY_CACHE", str(1 << 20))
        cache = query_cache_for(repo)
        assert cache is not None and cache.budget == 1 << 20
        assert query_cache_for(repo) is cache  # stable while budget holds

        query_filled(cache, "k", lambda: b"doc")
        assert cache.stats()["entries"] == 1
        # the ref-update drop hook (transport.service) releases the budget
        invalidate_query_caches(repo.gitdir)
        assert cache.stats() == {"entries": 0, "bytes": 0}
