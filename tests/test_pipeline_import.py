"""Pipelined import equivalence (ISSUE 5 tentpole): the bounded 4-stage
pipeline must be a pure performance transform — byte-identical root trees to
the serial path across source formats, an empty `kart diff --exit-code`
between a serial and a pipelined import of the same data, identical
--replace-ids incremental behaviour, and compiled-blob-encoder output
bit-identical to ``schema.encode_feature_blob``."""

import json
import os
import struct

import pytest

import kart_tpu.importer.importer as imp
from kart_tpu.core.repo import KartRepo
from kart_tpu.importer import ImportSource
from kart_tpu.importer.importer import import_sources

from helpers import create_points_gpkg


def _import_tree(tmp_path, name, spec, pipeline, monkeypatch, **kwargs):
    monkeypatch.setenv("KART_IMPORT_PIPELINE", "1" if pipeline else "0")
    repo = KartRepo.init_repository(str(tmp_path / name))
    commit_oid = import_sources(repo, ImportSource.open(spec), **kwargs)
    return repo, repo.odb.read_commit(commit_oid).tree


def _write_geojson(path, n):
    feats = [
        {
            "type": "Feature",
            "properties": {"id": i, "name": f"row-{i}", "score": i / 4.0},
            "geometry": {"type": "Point", "coordinates": [i * 0.5, -i * 0.25]},
        }
        for i in range(1, n + 1)
    ]
    path.write_text(
        json.dumps({"type": "FeatureCollection", "features": feats})
    )
    return str(path)


def _write_csv(path, n, dupes=()):
    rows = ["id,name,amount"]
    for i in range(1, n + 1):
        rows.append(f"{i},item-{i},{i * 1.5}")
    for i in dupes:  # duplicate pks: last occurrence must win on both paths
        rows.append(f"{i},item-{i}-replaced,{i * 2.5}")
    path.write_text("\n".join(rows) + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# root-tree equivalence across source formats
# ---------------------------------------------------------------------------


def test_pipelined_gpkg_matches_serial(tmp_path, monkeypatch):
    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=400)
    _, serial_tree = _import_tree(tmp_path, "serial", gpkg, False, monkeypatch)
    assert imp.LAST_IMPORT_PIPELINE is None  # serial path took no stages
    repo, pipe_tree = _import_tree(tmp_path, "pipe", gpkg, True, monkeypatch)
    assert serial_tree == pipe_tree
    # the pipeline genuinely ran: per-stage busy seconds were recorded
    stages = imp.LAST_IMPORT_PIPELINE
    assert stages is not None
    assert set(stages) == {"read", "encode", "hash", "pack", "tree", "wall"}
    assert stages["wall"] > 0
    # and every feature reads back through the odb
    ds = list(repo.structure("HEAD").datasets)[0]
    assert ds.feature_count == 400
    assert ds.get_feature(123)["name"] == "feature-123"


def test_pipelined_geojson_matches_serial(tmp_path, monkeypatch):
    spec = _write_geojson(tmp_path / "feats.geojson", 150)
    _, serial_tree = _import_tree(tmp_path, "serial", spec, False, monkeypatch)
    _, pipe_tree = _import_tree(tmp_path, "pipe", spec, True, monkeypatch)
    assert serial_tree == pipe_tree


def test_pipelined_csv_matches_serial_including_duplicate_pks(
    tmp_path, monkeypatch
):
    """Duplicate source pks resolve last-wins identically on both paths
    (git fast-import semantics)."""
    spec = _write_csv(tmp_path / "rows.csv", 120, dupes=(7, 42))
    _, serial_tree = _import_tree(tmp_path, "serial", spec, False, monkeypatch)
    repo, pipe_tree = _import_tree(tmp_path, "pipe", spec, True, monkeypatch)
    assert serial_tree == pipe_tree
    ds = list(repo.structure("HEAD").datasets)[0]
    assert ds.feature_count == 120
    assert ds.get_feature(42)["name"] == "item-42-replaced"


def test_pipelined_reimport_diffs_empty_via_cli(tmp_path, monkeypatch, cli_runner):
    """A serial import re-imported pipelined (--replace-existing) produces a
    commit with an EMPTY diff — `kart diff --exit-code` reports no changes
    between the serial and pipelined trees."""
    from kart_tpu.cli import cli

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=300)
    repo_dir = str(tmp_path / "repo")
    r = cli_runner.invoke(cli, ["init", repo_dir])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(repo_dir)
    monkeypatch.setenv("KART_IMPORT_PIPELINE", "0")
    r = cli_runner.invoke(cli, ["import", gpkg, "--no-checkout"])
    assert r.exit_code == 0, r.output
    monkeypatch.setenv("KART_IMPORT_PIPELINE", "1")
    r = cli_runner.invoke(
        cli, ["import", gpkg, "--no-checkout", "--replace-existing"]
    )
    assert r.exit_code == 0, r.output
    r = cli_runner.invoke(
        cli, ["diff", "HEAD^...HEAD", "--exit-code", "-o", "quiet"]
    )
    assert r.exit_code == 0, r.output  # 0 = no changes: trees identical


def test_pipelined_replace_ids_incremental_reimport(tmp_path, monkeypatch):
    """--replace-ids with the pipeline enabled behaves exactly like the
    serial incremental path: only the listed ids change."""
    import sqlite3

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=60)
    serial_repo, _ = _import_tree(tmp_path, "serial", gpkg, False, monkeypatch)
    pipe_repo, _ = _import_tree(tmp_path, "pipe", gpkg, True, monkeypatch)

    con = sqlite3.connect(gpkg)
    con.execute("UPDATE points SET name = 'edited' WHERE fid IN (3, 9)")
    con.execute("DELETE FROM points WHERE fid = 12")
    con.commit()
    con.close()

    trees = []
    for repo, pipeline in ((serial_repo, False), (pipe_repo, True)):
        monkeypatch.setenv("KART_IMPORT_PIPELINE", "1" if pipeline else "0")
        oid = import_sources(
            repo, ImportSource.open(gpkg), replace_ids=["3", "9", "12"]
        )
        trees.append(repo.odb.read_commit(oid).tree)
        ds = list(repo.structure("HEAD").datasets)[0]
        assert ds.get_feature(3)["name"] == "edited"
        assert ds.feature_count == 59  # fid 12 became a delete
    assert trees[0] == trees[1]


def test_native_reader_fallback_mid_stream_through_pipeline(
    tmp_path, monkeypatch, caplog
):
    """A row the native fused reader can't reproduce bit-identically
    (here: an envelope-bearing point, canonical storage has none) raises
    GpkgReaderFallback mid-stream; the pipelined import must restart
    through the Python encoder and still land on the serial tree."""
    import logging
    import sqlite3

    from kart_tpu import native

    if native.load_io() is None:
        native.ensure_built()
    if native.load_io() is None:
        pytest.skip("native IO lib not built")

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=200)
    x, y = 150.0, -45.0
    blob = (
        b"GP\x00" + bytes([0x01 | (1 << 1)])  # LE, env_kind=1 (XY envelope)
        + struct.pack("<i", 4326)
        + struct.pack("<4d", x, x, y, y)
        + struct.pack("<BI2d", 1, 1, x, y)
    )
    con = sqlite3.connect(gpkg)
    con.execute("UPDATE points SET geom = ? WHERE fid = 100", (blob,))
    con.commit()
    con.close()

    _, serial_tree = _import_tree(tmp_path, "serial", gpkg, False, monkeypatch)
    with caplog.at_level(logging.WARNING, logger="kart_tpu.importer"):
        repo, pipe_tree = _import_tree(tmp_path, "pipe", gpkg, True, monkeypatch)
    # the fallback genuinely fired (otherwise this test is vacuous)
    assert any("restarting import stream" in r.message for r in caplog.records)
    assert serial_tree == pipe_tree
    ds = list(repo.structure("HEAD").datasets)[0]
    assert ds.feature_count == 200
    assert ds.get_feature(100)["geom"] is not None


def test_pipeline_auto_skips_tiny_imports(tmp_path, monkeypatch):
    """In auto mode a tiny import stays serial (thread startup would cost
    more than it buys); the result is identical either way."""
    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=50)
    monkeypatch.delenv("KART_IMPORT_PIPELINE", raising=False)
    repo = KartRepo.init_repository(str(tmp_path / "auto"))
    import_sources(repo, ImportSource.open(gpkg))
    assert imp.LAST_IMPORT_PIPELINE is None  # serial path was chosen


# ---------------------------------------------------------------------------
# compiled blob encoder: bit-identity property test
# ---------------------------------------------------------------------------


def _gpkg_point(x, y):
    from kart_tpu.geometry import Geometry

    header = b"GP\x00\x01" + struct.pack("<i", 0)
    wkb = struct.pack("<BI2d", 1, 1, x, y)
    return Geometry(header + wkb)


def test_compiled_blob_encoder_bit_identical(tmp_path):
    from kart_tpu.models.dataset import compiled_blob_encoder
    from kart_tpu.models.schema import ColumnSchema, Schema

    cols = [
        ColumnSchema("a" * 40, "fid", "integer", 0, {"size": 64}),
        ColumnSchema(
            "b" * 40, "geom", "geometry", None,
            {"geometryType": "POINT", "geometryCRS": "EPSG:4326"},
        ),
        ColumnSchema("c" * 40, "name", "text", None, {}),
        ColumnSchema("d" * 40, "rating", "float", None, {"size": 64}),
        ColumnSchema("e" * 40, "flag", "boolean", None, {}),
        ColumnSchema("f" * 40, "data", "blob", None, {}),
        ColumnSchema("g" * 40, "count", "integer", None, {"size": 64}),
    ]
    schema = Schema(cols)
    encode = compiled_blob_encoder(schema)

    values = {
        # plain bytes in a geometry column: the generic hook bin-encodes
        # non-Geometry values, and the compiled path must match
        "geom": [
            _gpkg_point(1.5, -2.5),
            _gpkg_point(0.0, 0.0),
            None,
            bytes(_gpkg_point(3.0, 4.0)),
        ],
        "name": ["plain", "", "unicodé ☃", "\x00nul", None],
        "rating": [0.0, -1.75, 1e300, 5e-324, None],
        "flag": [True, False, None],
        "data": [b"", b"\x00\xff" * 50, None],
        "count": [0, -1, 2**62, -(2**62), 127, 128, 65536, None],
    }
    # cycle every column through its value list together — covers each
    # value at least once plus many cross-type combinations
    n = max(len(v) for v in values.values()) * 3
    for i in range(n):
        feature = {"fid": i + 1}
        for name, pool in values.items():
            feature[name] = pool[i % len(pool)]
        expected = schema.encode_feature_blob(feature)
        got = encode(feature)
        assert got == expected, feature
    # pk tuple type matches too
    pk, blob = encode({**{k: v[0] for k, v in values.items()}, "fid": 9})
    assert pk == (9,)


def test_import_iter_feature_blobs_accepts_sequences(tmp_path, monkeypatch):
    """The public import_iter_feature_blobs keeps accepting schema-ordered
    sequences (feature_to_raw_dict's other input shape) alongside dicts —
    the compiled encoder only handles dicts, so sequences fall back to the
    generic path with identical output."""
    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=30)
    repo, _ = _import_tree(tmp_path, "r", gpkg, False, monkeypatch)
    ds = list(repo.structure("HEAD").datasets)[0]
    feature = ds.get_feature(5)
    as_dict = dict(feature)
    as_seq = [feature[c.name] for c in ds.schema.columns]
    assert list(ds.import_iter_feature_blobs([as_dict])) == list(
        ds.import_iter_feature_blobs([as_seq])
    )


def test_compiled_blob_encoder_rejects_like_generic(tmp_path):
    """A value msgpack can't serialise fails identically on both paths."""
    from kart_tpu.models.dataset import compiled_blob_encoder
    from kart_tpu.models.schema import ColumnSchema, Schema

    schema = Schema(
        [
            ColumnSchema("a" * 40, "fid", "integer", 0, {"size": 64}),
            ColumnSchema("b" * 40, "blob_of_junk", "text", None, {}),
        ]
    )
    bad = {"fid": 1, "blob_of_junk": object()}
    with pytest.raises(TypeError):
        schema.encode_feature_blob(bad)
    with pytest.raises(TypeError):
        compiled_blob_encoder(schema)(bad)


# ---------------------------------------------------------------------------
# parallel worker-count satellites
# ---------------------------------------------------------------------------


def test_default_workers_cpu_count_fallbacks(monkeypatch):
    import kart_tpu.importer.parallel as par

    monkeypatch.delenv("KART_IMPORT_WORKERS", raising=False)
    monkeypatch.setattr(os, "cpu_count", lambda: None)
    assert par.default_workers() == 1
    monkeypatch.setattr(os, "cpu_count", lambda: 1)
    assert par.default_workers() == 1
    monkeypatch.setattr(os, "cpu_count", lambda: 2)
    assert par.default_workers() == 1  # 2 cores: in-process pipeline wins
    monkeypatch.setattr(os, "cpu_count", lambda: 8)
    assert par.default_workers() == 8
    monkeypatch.setenv("KART_IMPORT_WORKERS", "3")
    assert par.default_workers() == 3
    monkeypatch.setenv("KART_IMPORT_WORKERS", "junk")
    assert par.default_workers() == 8


def test_clamp_workers_limits_tiny_imports(monkeypatch):
    import kart_tpu.importer.parallel as par

    assert par.clamp_workers(8, 0) == 1
    assert par.clamp_workers(8, par.MIN_FEATURES_FOR_PARALLEL) == 1
    assert par.clamp_workers(8, 3 * par.MIN_FEATURES_FOR_PARALLEL) == 3
    assert par.clamp_workers(2, 10**9) == 2
    monkeypatch.setattr(par, "MIN_FEATURES_FOR_PARALLEL", 10)
    assert par.clamp_workers(4, 500) == 4
