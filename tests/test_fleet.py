"""Scale-out serving fleet (ISSUE 13; docs/FLEET.md): pull-replication
convergence (byte-identical refs + object stores under random push
interleavings), read-your-writes routing through a replica, byte-for-byte
proxied pushes (rebase/rejection parity with a direct primary push, one
trace end-to-end), and the commit-addressed peer cache tier."""

import hashlib
import json
import os
import threading
import time
import urllib.request
from urllib.parse import quote

import pytest

from kart_tpu import fleet as fleet_mod
from kart_tpu import telemetry, transport
from kart_tpu.core.repo import KartRepo
from kart_tpu.fleet import peercache
from kart_tpu.transport.http import HttpRemote, HttpTransportError, make_server
from kart_tpu.transport.protocol import ObjectEnumerator

from helpers import edit_commit, make_imported_repo


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    telemetry.reset()
    for var in (
        "KART_FAULTS",
        "KART_REPLICA_OF",
        "KART_REPLICA_POLL_SECONDS",
        "KART_REPLICA_MAX_LAG",
        "KART_PEER_CACHE",
        "KART_TILE_CACHE",
        "KART_SERVE_ENUM_CACHE",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("KART_TRANSPORT_RETRY_BASE", "0.01")
    monkeypatch.setenv("KART_TRANSPORT_RETRY_CAP", "0.05")
    with peercache._peer_caches_lock:
        peercache._PEER_CACHES.clear()
    with peercache._peer_down_lock:
        peercache._peer_down.clear()
    yield
    telemetry.reset()


def serve_in_thread(repo, fleet=None):
    server = make_server(repo, fleet=fleet)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


@pytest.fixture()
def primary(tmp_path):
    (tmp_path / "primary").mkdir()
    repo, ds_path = make_imported_repo(tmp_path / "primary", n=12)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    server, url = serve_in_thread(repo)
    yield repo, ds_path, url
    server.shutdown()
    server.server_close()


def make_replica(tmp_path, primary_url, name="replica", peers=(), sync=True):
    repo = KartRepo.init_repository(str(tmp_path / name))
    node = fleet_mod.FleetNode(repo, primary_url=primary_url, peers=peers)
    if sync:
        node.sync.sync_once()
    server, url = serve_in_thread(repo, fleet=node)
    return repo, node, server, url


def refs_of(repo):
    return dict(repo.refs.iter_refs("refs/"))


def odb_digest(repo):
    """Content digest of the object store: equal digests = byte-identical
    stores (oid = content address, so the sorted oid set pins every byte)."""
    h = hashlib.sha256()
    for oid in sorted(repo.odb.iter_oids()):
        h.update(oid.encode())
    return h.hexdigest()


def counter(name, **labels):
    for n, l, v in telemetry.snapshot()["counters"]:
        if n == name and l == labels:
            return v
    return 0


def raw_push(url, repo, new_oid, *, old_oid, ref="refs/heads/main",
             client=None):
    """Drive receive-pack directly so tests pick the CAS base and keep the
    client instance (the read-your-writes pin lives on it)."""
    from kart_tpu.transport.http import have_closure
    from kart_tpu.transport.remote import read_shallow
    from kart_tpu.transport.retry import RetryPolicy

    client = client or HttpRemote(url, retry=RetryPolicy(attempts=1))
    info = client.ls_refs()
    server_refs = {f"refs/heads/{b}": o for b, o in info["heads"].items()}
    has = have_closure(
        repo.odb, list(server_refs.values()), info.get("shallow", ())
    )
    enum = ObjectEnumerator(
        repo.odb, [new_oid], has=has.__contains__,
        sender_shallow=read_shallow(repo),
    )
    return client.receive_pack(
        enum,
        [{"ref": ref, "old": old_oid, "new": new_oid, "force": False}],
        shallow=lambda: enum.shallow_boundary,
    )


# ---------------------------------------------------------------------------
# replication: the sync loop over the exclusion lane
# ---------------------------------------------------------------------------


def test_sync_mirrors_refs_and_objects(primary, tmp_path):
    repo, ds_path, url = primary
    replica = KartRepo.init_repository(str(tmp_path / "r"))
    node = fleet_mod.FleetNode(replica, primary_url=url)
    first = node.sync.sync_once()
    assert first["objects"] > 0 and first["advanced"] == 1
    assert refs_of(replica) == refs_of(repo)
    assert odb_digest(replica) == odb_digest(repo)
    # the second cycle is a no-op: oid-exclusion/haves mean zero re-ship
    second = node.sync.sync_once()
    assert second == {
        "objects": 0, "advanced": 0, "deleted": 0, "in_sync": True
    }


def test_sync_ships_only_the_delta(primary, tmp_path):
    repo, ds_path, url = primary
    replica = KartRepo.init_repository(str(tmp_path / "r"))
    node = fleet_mod.FleetNode(replica, primary_url=url)
    initial = node.sync.sync_once()
    edit_commit(
        repo, ds_path,
        updates=[{"fid": 1, "geom": None, "name": "delta", "rating": 1.0}],
        message="one more commit",
    )
    delta = node.sync.sync_once()
    # one commit, its changed tree spine and the one changed blob — a
    # strict fraction of the full store, not a re-clone
    assert 0 < delta["objects"] < initial["objects"]
    assert refs_of(replica) == refs_of(repo)


def test_sync_deletes_vanished_branches(primary, tmp_path):
    repo, ds_path, url = primary
    tip = repo.refs.get("refs/heads/main")
    repo.refs.set("refs/heads/dev", tip, log_message="test")
    replica = KartRepo.init_repository(str(tmp_path / "r"))
    node = fleet_mod.FleetNode(replica, primary_url=url)
    node.sync.sync_once()
    assert replica.refs.get("refs/heads/dev") == tip
    repo.refs.delete("refs/heads/dev")
    result = node.sync.sync_once()
    assert result["deleted"] == 1
    assert replica.refs.get("refs/heads/dev") is None
    assert refs_of(replica) == refs_of(repo)


def test_convergence_under_random_interleavings(primary, tmp_path):
    """The replication convergence property: random pushes landing on the
    primary, two replicas syncing at arbitrary interleaved moments — after
    a final cycle each, both replicas' refs and object stores are
    byte-identical to each other and to the primary."""
    import random

    rng = random.Random(13)
    repo, ds_path, url = primary
    r1 = KartRepo.init_repository(str(tmp_path / "r1"))
    r2 = KartRepo.init_repository(str(tmp_path / "r2"))
    n1 = fleet_mod.FleetNode(r1, primary_url=url)
    n2 = fleet_mod.FleetNode(r2, primary_url=url)
    nodes = [n1, n2]
    fid = 1
    for _round in range(8):
        action = rng.random()
        if action < 0.6:
            fid += 1
            edit_commit(
                repo, ds_path,
                updates=[{
                    "fid": (fid % 12) + 1, "geom": None,
                    "name": f"round-{_round}", "rating": float(_round),
                }],
                message=f"storm commit {_round}",
            )
        elif action < 0.8:
            repo.refs.set(
                f"refs/heads/b{_round}",
                repo.refs.get("refs/heads/main"),
                log_message="branch",
            )
        # a random subset of replicas syncs mid-storm, in random order
        for node in rng.sample(nodes, rng.randint(0, 2)):
            node.sync.sync_once()
    for node in nodes:
        node.sync.sync_once()
    assert refs_of(r1) == refs_of(r2) == refs_of(repo)
    assert odb_digest(r1) == odb_digest(r2) == odb_digest(repo)


def test_replica_serves_reads_with_primary_down(primary, tmp_path):
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        # reads are answered from local state: no primary round-trip, so
        # they keep working when the primary is unreachable
        node.sync.stop()
        dead = fleet_mod.FleetNode(replica, primary_url="http://127.0.0.1:9")
        server.fleet = dead
        client = HttpRemote(rurl)
        info = client.ls_refs()
        assert info["heads"]["main"] == repo.refs.get("refs/heads/main")
        dst = KartRepo.init_repository(str(tmp_path / "c"))
        header = client.fetch_pack(dst, list(info["heads"].values()))
        assert header["object_count"] > 0
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# routing: proxied writes + read-your-writes
# ---------------------------------------------------------------------------


def test_push_through_replica_lands_on_primary(primary, tmp_path):
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    node.start()
    try:
        clone = transport.clone(rurl, str(tmp_path / "c"), do_checkout=False)
        clone.config.set_many(
            {"user.name": "w", "user.email": "w@example.com"}
        )
        new_oid = edit_commit(
            clone, ds_path,
            updates=[{"fid": 3, "geom": None, "name": "via-replica",
                      "rating": 9.0}],
            message="proxied push",
        )
        updated = transport.push(clone, "origin")
        assert updated["refs/heads/main"] == new_oid
        # the write landed on the PRIMARY (the replica never lands writes)
        assert repo.refs.get("refs/heads/main") == new_oid
        assert node.status_dict()["proxied_writes"] == 1
        # the proxied write kicked the sync loop: the replica converges
        # without waiting out a poll interval
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if replica.refs.get("refs/heads/main") == new_oid:
                break
            time.sleep(0.05)
        assert replica.refs.get("refs/heads/main") == new_oid
    finally:
        node.stop()
        server.shutdown()
        server.server_close()


def test_read_your_writes_through_same_replica(primary, tmp_path):
    """The regression the RYW machinery exists for: push through a
    replica, immediately read the new tip through the same replica — the
    read must see the pushed commit, never the replica's stale view."""
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    node.start()
    try:
        clone = transport.clone(rurl, str(tmp_path / "c"), do_checkout=False)
        clone.config.set_many(
            {"user.name": "w", "user.email": "w@example.com"}
        )
        new_oid = edit_commit(
            clone, ds_path,
            updates=[{"fid": 5, "geom": None, "name": "ryw", "rating": 1.0}],
            message="ryw",
        )
        client = HttpRemote(rurl)
        old = client.ls_refs()["heads"]["main"]
        result = raw_push(rurl, clone, new_oid, old_oid=old, client=client)
        assert result["updated"]["refs/heads/main"] == new_oid
        assert client._min_commit == new_oid  # the pin was taken
        # immediately: the same client's read stalls until the replica's
        # tips contain the pushed commit, then answers locally
        info = client.ls_refs()
        assert info["heads"]["main"] == new_oid
        assert node.status_dict()["ryw_stalls"] >= 1
    finally:
        node.stop()
        server.shutdown()
        server.server_close()


def test_ryw_pins_to_primary_past_lag_bound(primary, tmp_path, monkeypatch):
    """A replica that cannot catch up inside KART_REPLICA_MAX_LAG answers
    the pinned read from the primary itself (never a stale view)."""
    monkeypatch.setenv("KART_REPLICA_MAX_LAG", "0.2")
    repo, ds_path, url = primary
    # sync thread deliberately NOT started: the replica can never catch up
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        clone = transport.clone(rurl, str(tmp_path / "c"), do_checkout=False)
        clone.config.set_many(
            {"user.name": "w", "user.email": "w@example.com"}
        )
        new_oid = edit_commit(
            clone, ds_path,
            updates=[{"fid": 6, "geom": None, "name": "pin", "rating": 2.0}],
            message="pin",
        )
        client = HttpRemote(rurl)
        old = client.ls_refs()["heads"]["main"]
        raw_push(rurl, clone, new_oid, old_oid=old, client=client)
        info = client.ls_refs()  # proxied to the primary
        assert info["heads"]["main"] == new_oid
        assert node.status_dict()["ryw_pins"] >= 1
        # the replica itself is still behind — the pin, not luck, answered
        assert replica.refs.get("refs/heads/main") != new_oid
    finally:
        server.shutdown()
        server.server_close()


def test_malformed_min_commit_header_is_ignored(primary, tmp_path):
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        req = urllib.request.Request(
            f"{rurl}/api/v1/refs",
            headers={fleet_mod.MIN_COMMIT_HEADER: "not-a-commit"},
        )
        t0 = time.monotonic()
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
        assert time.monotonic() - t0 < 5.0  # no lag-bound stall
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# proxied-push parity: same payloads, same trace as a direct primary push
# ---------------------------------------------------------------------------


def _conflicting_loser(repo, ds_path, url, tmp_path):
    """Two clones race one feature; the winner lands directly on the
    primary — returns the loser clone + its conflicting commit."""
    winner = transport.clone(url, str(tmp_path / "winner"), do_checkout=False)
    winner.config.set_many({"user.name": "w", "user.email": "w@example.com"})
    loser = transport.clone(url, str(tmp_path / "loser"), do_checkout=False)
    loser.config.set_many({"user.name": "l", "user.email": "l@example.com"})
    edit_commit(
        winner, ds_path,
        updates=[{"fid": 7, "geom": None, "name": "winner", "rating": 1.0}],
        message="winner",
    )
    loser_oid = edit_commit(
        loser, ds_path,
        updates=[{"fid": 7, "geom": None, "name": "loser", "rating": 2.0}],
        message="loser",
    )
    transport.push(winner, "origin")
    return loser, loser_oid


def test_proxied_push_conflict_report_byte_identical(
    primary, tmp_path, monkeypatch
):
    """A rejected contended push through a replica carries the PR 8
    structured report byte-for-byte identical to a direct primary push on
    BOTH transports — the proxy relays the primary's response body
    unmodified, and the report document itself is transport-independent."""
    from kart_tpu.transport.stdio import StdioRemote, StdioTransportError
    from test_ssh_transport import _install_fake_ssh

    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        loser, loser_oid = _conflicting_loser(repo, ds_path, url, tmp_path)
        base = loser.refs.get("refs/remotes/origin/main")
        with pytest.raises(HttpTransportError) as direct:
            raw_push(url, loser, loser_oid, old_oid=base)
        with pytest.raises(HttpTransportError) as proxied:
            raw_push(rurl, loser, loser_oid, old_oid=base)
        assert direct.value.terminal and proxied.value.terminal
        assert json.dumps(direct.value.conflict_report, sort_keys=True) == \
            json.dumps(proxied.value.conflict_report, sort_keys=True)
        assert str(direct.value).replace(url, "") == \
            str(proxied.value).replace(rurl, "")
        # the stdio transport's direct push reports the identical document
        _install_fake_ssh(tmp_path, monkeypatch)
        ssh_client = StdioRemote(f"testhost:{repo.workdir or repo.gitdir}")
        try:
            with pytest.raises(StdioTransportError) as ssh_direct:
                raw_push(None, loser, loser_oid, old_oid=base,
                         client=ssh_client)
        finally:
            ssh_client.close()
        assert ssh_direct.value.terminal
        assert json.dumps(
            ssh_direct.value.conflict_report, sort_keys=True
        ) == json.dumps(proxied.value.conflict_report, sort_keys=True)
    finally:
        server.shutdown()
        server.server_close()


def test_proxied_push_rebase_payload_identical(primary, tmp_path):
    """A clean contended push auto-rebases on the primary; the proxied
    response carries the identical rebase payload."""
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        winner = transport.clone(
            url, str(tmp_path / "w2"), do_checkout=False
        )
        winner.config.set_many(
            {"user.name": "w", "user.email": "w@example.com"}
        )
        loser = transport.clone(rurl, str(tmp_path / "l2"), do_checkout=False)
        loser.config.set_many(
            {"user.name": "l", "user.email": "l@example.com"}
        )
        edit_commit(
            winner, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "w", "rating": 1.0}],
            message="winner",
        )
        loser_oid = edit_commit(
            loser, ds_path,
            updates=[{"fid": 12, "geom": None, "name": "l", "rating": 2.0}],
            message="loser disjoint",
        )
        transport.push(winner, "origin")
        base = loser.refs.get("refs/remotes/origin/main")
        result = raw_push(rurl, loser, loser_oid, old_oid=base)
        assert result["rebase"]["rebased"] == 1
        assert result["rebase"]["mode"] == "merge"
        landed = result["updated"]["refs/heads/main"]
        assert repo.refs.get("refs/heads/main") == landed
    finally:
        server.shutdown()
        server.server_close()


def test_proxied_push_carries_one_trace_end_to_end(
    primary, tmp_path, monkeypatch
):
    """The traceparent survives the hop: the client's trace id appears on
    BOTH the replica's and the primary's access records for one proxied
    push — the PR 11 cross-process join holds through the relay."""
    from kart_tpu.telemetry import context as rq_context

    log_path = str(tmp_path / "access.jsonl")
    monkeypatch.setenv("KART_ACCESS_LOG", log_path)
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        clone = transport.clone(rurl, str(tmp_path / "c"), do_checkout=False)
        clone.config.set_many(
            {"user.name": "w", "user.email": "w@example.com"}
        )
        new_oid = edit_commit(
            clone, ds_path,
            updates=[{"fid": 2, "geom": None, "name": "t", "rating": 3.0}],
            message="traced",
        )
        with rq_context.request_scope(verb="push") as ctx:
            old = HttpRemote(rurl).ls_refs()["heads"]["main"]
            raw_push(rurl, clone, new_oid, old_oid=old)
            trace_id = ctx.trace_id
        with open(log_path) as f:
            records = [json.loads(line) for line in f if line.strip()]
        receives = [
            r for r in records
            if r["verb"] == "receive-pack" and r.get("trace_id") == trace_id
        ]
        # one logical push, two servers touched (replica relay + primary
        # landing), one trace joining them
        assert len(receives) == 2
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# the peer cache tier
# ---------------------------------------------------------------------------


def test_tile_peer_fill_byte_identical(primary, tmp_path):
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(
        tmp_path, url, peers=(url,)
    )
    try:
        tile_path = f"/api/v1/tiles/main/{quote(ds_path, safe='')}/0/0/0"
        direct = urllib.request.urlopen(url + tile_path, timeout=10)
        direct_body = direct.read()
        fetches0 = counter("fleet.peer_cache.fetches")
        via = urllib.request.urlopen(rurl + tile_path, timeout=10)
        via_body = via.read()
        assert via_body == direct_body
        assert via.headers["ETag"] == direct.headers["ETag"]
        # the replica fetched from its peer instead of encoding locally
        assert counter("fleet.peer_cache.fetches") == fetches0 + 1
        # second request: a peer-cache memo hit, no second peer round-trip
        hits0 = counter("fleet.peer_cache.hits")
        again = urllib.request.urlopen(rurl + tile_path, timeout=10).read()
        assert again == direct_body
        assert counter("fleet.peer_cache.hits") == hits0 + 1
        assert counter("fleet.peer_cache.fetches") == fetches0 + 1
    finally:
        server.shutdown()
        server.server_close()


def test_fetch_pack_peer_fill_serves_complete_clone(primary, tmp_path):
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(
        tmp_path, url, peers=(url,)
    )
    try:
        client = HttpRemote(rurl)
        wants = list(client.ls_refs()["heads"].values())
        dst = KartRepo.init_repository(str(tmp_path / "c"))
        fetches0 = counter("fleet.peer_cache.fetches")
        header = client.fetch_pack(dst, wants)
        assert counter("fleet.peer_cache.fetches") == fetches0 + 1
        # every object landed — the peer-relayed framed response is whole
        assert header["object_count"] == sum(1 for _ in dst.odb.iter_oids())
        assert odb_digest(dst) == odb_digest(replica)
    finally:
        server.shutdown()
        server.server_close()


def test_peer_failure_falls_back_to_local_compute(primary, tmp_path):
    """A dead peer costs one failed probe, then local compute answers —
    the peer tier is an optimisation, never a dependency."""
    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(
        tmp_path, url, peers=("http://127.0.0.1:9",)
    )
    try:
        tile_path = f"/api/v1/tiles/main/{quote(ds_path, safe='')}/0/0/0"
        failures0 = counter("fleet.peer_cache.fetch_failures")
        body = urllib.request.urlopen(rurl + tile_path, timeout=30).read()
        assert body == urllib.request.urlopen(url + tile_path).read()
        assert counter("fleet.peer_cache.fetch_failures") == failures0 + 1
    finally:
        server.shutdown()
        server.server_close()


def test_ryw_pinned_fetch_relays_post_verbs(primary, tmp_path, monkeypatch):
    """Regression: the pin must ride the POST data-fetch verbs too, and a
    pinned fetch-pack past the lag bound must be relayed body-and-all —
    an ungated (or GET-relayed) fetch from the stale store would miss
    exactly the objects the pin guarantees."""
    monkeypatch.setenv("KART_REPLICA_MAX_LAG", "0.2")
    repo, ds_path, url = primary
    # sync thread deliberately NOT started: the replica stays stale
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        clone = transport.clone(rurl, str(tmp_path / "c"), do_checkout=False)
        clone.config.set_many(
            {"user.name": "w", "user.email": "w@example.com"}
        )
        new_oid = edit_commit(
            clone, ds_path,
            updates=[{"fid": 8, "geom": None, "name": "pf", "rating": 4.0}],
            message="pinned fetch",
        )
        client = HttpRemote(rurl)
        old = client.ls_refs()["heads"]["main"]
        raw_push(rurl, clone, new_oid, old_oid=old, client=client)
        # the same pinned client clones from scratch: ls-refs AND
        # fetch-pack both answer from the primary, so the new commit and
        # its whole closure arrive despite the stale replica
        dst = KartRepo.init_repository(str(tmp_path / "fresh"))
        wants = list(client.ls_refs()["heads"].values())
        assert new_oid in wants
        client.fetch_pack(dst, wants)
        assert dst.odb.contains(new_oid)
        assert not replica.odb.contains(new_oid)  # the pin, not the sync
    finally:
        server.shutdown()
        server.server_close()


def test_mutually_peered_replicas_do_not_recurse(primary, tmp_path):
    """Regression: replicas listing each other as peers must not loop — a
    fill request carries X-Kart-Peer-Fill and is answered from local
    state, so a cold tile costs one hop, not a recursion that wedges
    behind the asker's own single-flight token until the fetch timeout."""
    repo, ds_path, url = primary
    ra_repo, ra_node, ra_server, ra_url = make_replica(
        tmp_path, url, name="ra"
    )
    try:
        rb_repo, rb_node, rb_server, rb_url = make_replica(
            tmp_path, url, name="rb", peers=(ra_url,)
        )
        try:
            ra_node.peers = (rb_url,)  # now they peer each other
            tile_path = f"/api/v1/tiles/main/{quote(ds_path, safe='')}/0/0/0"
            direct = urllib.request.urlopen(url + tile_path, timeout=10).read()
            t0 = time.monotonic()
            via_a = urllib.request.urlopen(
                ra_url + tile_path, timeout=30
            ).read()
            elapsed = time.monotonic() - t0
            assert via_a == direct
            # well under PEER_FETCH_TIMEOUT: B answered A's fill locally
            # instead of recursing back into A
            assert elapsed < peercache.PEER_FETCH_TIMEOUT / 2, elapsed
        finally:
            rb_server.shutdown()
            rb_server.server_close()
    finally:
        ra_server.shutdown()
        ra_server.server_close()


def test_pin_ignores_non_head_refs():
    """Regression: only refs/heads/* oids may pin — a tag oid can never
    satisfy the replica's branch-tip containment and would stall every
    later read for the full lag bound."""
    from kart_tpu.fleet import router

    doc = {
        "updated": {
            "refs/tags/v1": "a" * 40,
            "refs/heads/main": "b" * 40,
            "refs/heads/gone": None,
        }
    }
    assert router.landed_head_oids(doc) == ["b" * 40]
    assert router.landed_head_oids({"updated": {"refs/tags/v1": "a" * 40}}) == []
    assert router.landed_head_oids({}) == []
    assert router.landed_head_oids(None) == []


# ---------------------------------------------------------------------------
# configuration + operator surfaces
# ---------------------------------------------------------------------------


def test_node_from_env(primary, tmp_path, monkeypatch):
    repo, _ds, url = primary
    r = KartRepo.init_repository(str(tmp_path / "r"))
    assert fleet_mod.node_from_env(r) is None
    monkeypatch.setenv("KART_REPLICA_OF", url)
    monkeypatch.setenv("KART_PEER_CACHE", "primary")
    node = fleet_mod.node_from_env(r)
    assert node.is_replica and node.primary_url == url
    assert node.peers == (url,)
    monkeypatch.setenv("KART_PEER_CACHE", "0")
    assert fleet_mod.node_from_env(r).peers == ()
    monkeypatch.delenv("KART_REPLICA_OF")
    monkeypatch.setenv(
        "KART_PEER_CACHE", f"{url}/, {url}"
    )
    peers_only = fleet_mod.node_from_env(r)
    assert not peers_only.is_replica
    assert peers_only.peers == (url,)  # normalised + de-duplicated


def test_stats_payload_and_fleet_status_cli(primary, tmp_path):
    from click.testing import CliRunner

    from kart_tpu.cli import cli
    from kart_tpu.cli.fleet_cmds import member_status

    repo, ds_path, url = primary
    replica, node, server, rurl = make_replica(tmp_path, url)
    try:
        doc = json.loads(
            urllib.request.urlopen(
                f"{rurl}/api/v1/stats?format=json", timeout=10
            ).read()
        )
        fleet_block = doc["fleet"]
        assert fleet_block["role"] == "replica"
        assert fleet_block["primary"] == url
        assert fleet_block["sync_cycles"] >= 1
        assert fleet_block["lag_seconds"] is not None
        status = member_status(doc)
        assert status["role"] == "replica"

        r = CliRunner().invoke(
            cli, ["fleet", "status", url, rurl], catch_exceptions=False
        )
        assert r.exit_code == 0, r.output
        assert "replica" in r.output and "primary" in r.output
        r = CliRunner().invoke(
            cli, ["fleet", "status", "-o", "json", rurl],
            catch_exceptions=False,
        )
        assert r.exit_code == 0, r.output
        parsed = json.loads(r.output)
        assert parsed[rurl]["role"] == "replica"

        # kart top renders the replication-lag line
        r = CliRunner().invoke(
            cli, ["top", "--once", rurl], catch_exceptions=False
        )
        assert r.exit_code == 0, r.output
        assert "replica of" in r.output and "lag" in r.output
    finally:
        server.shutdown()
        server.server_close()
