"""Live-update events (ISSUE 14; docs/EVENTS.md): CDC dirty-tile
exactness against full re-encodes, event-log resume-by-sequence, the
warm-then-announce protocol, long-poll/SSE serving, missed-emission
replay across a server restart (including a real SIGKILL), and the fleet
subscription legs (event-kicked replication lag, read-your-writes by
sequence)."""

import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kart_tpu import events as events_mod
from kart_tpu import telemetry, tiles
from kart_tpu.core.repo import KartRepo
from kart_tpu.events import cdc
from kart_tpu.events.log import EventLog
from kart_tpu.tiles.encode import encode_tile, parse_payload
from kart_tpu.tiles.grid import tile_range_for_bbox
from kart_tpu.transport.http import HttpRemote, make_server

from helpers import edit_commit, gpkg_point, make_imported_repo
from kart_tpu.geometry import Geometry


def gpoint(x, y):
    return Geometry(gpkg_point(x, y))


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    telemetry.reset()
    for var in (
        "KART_FAULTS",
        "KART_SERVE_EVENTS",
        "KART_EVENTS_LOG_SIZE",
        "KART_EVENTS_WARM_BUDGET",
        "KART_WATCH_TIMEOUT",
        "KART_TILE_CACHE",
        "KART_REPLICA_OF",
        "KART_PEER_CACHE",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("KART_TRANSPORT_RETRY_BASE", "0.01")
    monkeypatch.setenv("KART_TRANSPORT_RETRY_CAP", "0.05")
    yield
    events_mod.drop_emitters()
    telemetry.reset()


def wait_for(pred, timeout=30.0, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = pred()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def serve_in_thread(repo, fleet=None):
    server = make_server(repo, fleet=fleet)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def get_json(url, timeout=40):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def gauge(name):
    for n, _labels, v in telemetry.snapshot()["gauges"]:
        if n == name:
            return v
    return None


# ---------------------------------------------------------------------------
# CDC exactness: the dirty-tile set equals the payload-content diff
# ---------------------------------------------------------------------------


def payload_content(repo, commit_oid, ds_path, z, x, y):
    """(header minus the pinned commit, layer bytes) — "content" for the
    exactness property (the header embeds the commit oid by design)."""
    source = tiles.source_for(repo, commit_oid, ds_path)
    payload, _stats = encode_tile(source, z, x, y, max_features=0)
    header, layers = parse_payload(payload)
    header.pop("commit")
    return header, layers


def brute_force_dirty(repo, old_oid, new_oid, ds_path, zooms, pad_tiles=1):
    """The ground truth: re-encode every candidate tile at both commits
    and compare content. Candidates per zoom are the (±pad_tiles-margined)
    range covering the union bbox of every envelope at either commit —
    tiles outside hold no feature at either commit, so their content is
    identical by construction."""
    envs = np.concatenate(
        [
            np.asarray(tiles.source_for(repo, oid, ds_path).envelopes(),
                       dtype=np.float64)
            for oid in (old_oid, new_oid)
        ]
    )
    finite = envs[np.isfinite(envs).all(axis=1)]
    full_world = len(finite) < len(envs)
    bbox = (
        (-180.0, -90.0, 180.0, 90.0)
        if full_world or not len(finite)
        else (
            float(finite[:, 0].min()), float(finite[:, 1].min()),
            float(finite[:, 2].max()), float(finite[:, 3].max()),
        )
    )
    dirty = {z: set() for z in zooms}
    for z in zooms:
        n = 1 << z
        x0, y0, x1, y1 = tile_range_for_bbox(z, bbox)
        x0, y0 = max(0, x0 - pad_tiles), max(0, y0 - pad_tiles)
        x1, y1 = min(n - 1, x1 + pad_tiles), min(n - 1, y1 + pad_tiles)
        for x in range(x0, x1 + 1):
            for y in range(y0, y1 + 1):
                if payload_content(
                    repo, old_oid, ds_path, z, x, y
                ) != payload_content(repo, new_oid, ds_path, z, x, y):
                    dirty[z].add((x, y))
    return dirty


def cdc_dirty_sets(repo, old_oid, new_oid, ds_path, zooms):
    summary = cdc.dirty_tiles(repo, old_oid, new_oid, zooms=zooms)
    entry = summary.get(ds_path)
    if entry is None:
        return {z: set() for z in zooms}
    assert entry["truncated"] is False
    return {
        z: {tuple(t) for t in entry["tiles"].get(str(z), [])} for z in zooms
    }


def random_edits(rng, live_fids, next_fid, region):
    """A random mixed edit: inserts (new points), geometry moves,
    attribute-only updates (same envelope, changed blob — the geojson
    exactness case), deletes."""
    w, s, e, n = region

    def point():
        return gpoint(rng.uniform(w, e), rng.uniform(s, n))

    committed = list(live_fids)  # fids that exist at the current tip
    inserts = []
    for _ in range(rng.randrange(0, 3)):
        inserts.append(
            {"fid": next_fid, "geom": point(),
             "name": f"new{next_fid}", "rating": rng.random()}
        )
        live_fids.append(next_fid)
        next_fid += 1
    updates = []
    for fid in rng.sample(committed, min(len(committed), rng.randrange(1, 4))):
        if rng.random() < 0.4:
            # attribute-only: envelope identical, oid changes
            updates.append(
                {"fid": fid, "geom": None, "name": f"attr{fid}",
                 "rating": rng.random()}
            )
        else:
            updates.append(
                {"fid": fid, "geom": point(), "name": f"moved{fid}",
                 "rating": rng.random()}
            )
    deletes = []
    candidates = [f for f in committed if not any(
        u["fid"] == f for u in updates)]
    for fid in rng.sample(candidates, min(len(candidates),
                                          rng.randrange(0, 2))):
        deletes.append(fid)
        live_fids.remove(fid)
    return inserts, updates, deletes, next_fid


def test_cdc_dirty_tiles_exact_random_edits(tmp_path):
    """The acceptance property: for random edit commits, the CDC set ==
    the set of tiles whose payload content actually differs — checked in
    BOTH directions (superset-free and subset-free) against a full
    re-encode of every candidate tile."""
    repo, ds_path = make_imported_repo(tmp_path, n=40)
    rng = random.Random(1234)
    zooms = tuple(range(0, 6))
    live_fids = list(range(1, 41))
    next_fid = 1000
    region = (100.0, -46.0, 141.0, -34.0)  # the fixture's point spread

    tip = repo.refs.get("refs/heads/main")
    for round_no in range(4):
        inserts, updates, deletes, next_fid = random_edits(
            rng, live_fids, next_fid, region
        )
        new_tip = edit_commit(
            repo, ds_path, inserts=inserts, updates=updates,
            deletes=deletes, message=f"random edit {round_no}",
        )
        got = cdc_dirty_sets(repo, tip, new_tip, ds_path, zooms)
        want = brute_force_dirty(repo, tip, new_tip, ds_path, zooms)
        assert got == want, f"round {round_no}: CDC != re-encode diff"
        assert any(want.values())  # the rounds actually dirty something
        tip = new_tip


def test_cdc_exact_on_null_geometry_polar_and_antimeridian(tmp_path):
    """The fail-open/edge geometry cases: a NULL-geometry feature
    (full-world envelope — in every tile), a polar point (served by the
    clamped edge row), an anti-meridian-hugging point."""
    repo, ds_path = make_imported_repo(tmp_path, n=6)
    zooms = tuple(range(0, 4))
    tip = repo.refs.get("refs/heads/main")

    steps = [
        # insert a NULL-geometry row: every tile's geojson layer changes
        dict(inserts=[{"fid": 900, "geom": None, "name": "null", "rating": 0.1}]),
        # polar + antimeridian inserts
        dict(inserts=[
            {"fid": 901, "geom": gpoint(12.0, 88.5), "name": "polar",
             "rating": 0.2},
            {"fid": 902, "geom": gpoint(179.999, -30.0), "name": "am",
             "rating": 0.3},
        ]),
        # touch the NULL-geometry row's attributes only
        dict(updates=[{"fid": 900, "geom": None, "name": "null2",
                       "rating": 0.4}]),
        # delete the polar row
        dict(deletes=[901]),
    ]
    for i, step in enumerate(steps):
        new_tip = edit_commit(repo, ds_path, message=f"edge {i}", **step)
        got = cdc_dirty_sets(repo, tip, new_tip, ds_path, zooms)
        want = brute_force_dirty(repo, tip, new_tip, ds_path, zooms)
        assert got == want, f"step {i}: CDC != re-encode diff"
        tip = new_tip


def test_cdc_skips_identical_datasets_and_counts_changes(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=8)
    tip = repo.refs.get("refs/heads/main")
    assert cdc.dirty_tiles(repo, tip, tip) == {}
    new_tip = edit_commit(
        repo, ds_path,
        inserts=[{"fid": 500, "geom": gpoint(170.0, -40.0),
                  "name": "a", "rating": 1.0}],
        deletes=[1],
        message="one in one out",
    )
    summary = cdc.dirty_tiles(repo, tip, new_tip)
    entry = summary[ds_path]
    assert entry["changed"] == {"inserts": 1, "deletes": 1}
    assert entry["tile_count"] > 0 and entry["bbox"] is not None


def test_cdc_derives_pushed_tip_sidecar_o_changed(tmp_path):
    """A pushed tip arrives with no sidecar: the CDC must derive it from
    the old tip's via the tree delta (no O(N) rebuild) and produce the
    same exact dirty set — and the derived file then serves the tile
    source too."""
    from kart_tpu.diff import sidecar
    from kart_tpu.tiles.source import drop_sources

    repo, ds_path = make_imported_repo(tmp_path, n=30)
    tip = repo.refs.get("refs/heads/main")
    new_tip = edit_commit(
        repo, ds_path,
        inserts=[{"fid": 700, "geom": gpoint(105.0, -38.0),
                  "name": "pushed", "rating": 1.0}],
        updates=[{"fid": 3, "geom": gpoint(130.0, -44.0),
                  "name": "moved", "rating": 2.0}],
        deletes=[7],
        message="simulated push",
    )
    zooms = tuple(range(0, 5))
    want = brute_force_dirty(repo, tip, new_tip, ds_path, zooms)
    # simulate the server-side state after a push: the new tree's sidecar
    # does not exist locally (commit_diff derived one — delete it)
    new_ds = repo.structure(new_tip).datasets[ds_path]
    path = sidecar.sidecar_file(repo, new_ds.feature_tree.oid)
    if os.path.exists(path):
        os.remove(path)
    drop_sources(repo.gitdir)
    got = cdc_dirty_sets(repo, tip, new_tip, ds_path, zooms)
    assert got == want
    # the derivation ran (the sidecar exists again, content-addressed),
    # and it carried the envelope columns when the old one had them
    assert os.path.exists(path)
    derived = sidecar.load_block(repo, new_ds, pad=False)
    old_ds = repo.structure(tip).datasets[ds_path]
    old_block = sidecar.load_block(repo, old_ds, pad=False)
    assert derived.count == old_block.count  # +1 insert -1 delete
    assert (derived.envelopes is not None) == (
        old_block.envelopes is not None
    )


def test_tiles_for_envelopes_cap_reports_incomplete_enumeration():
    """The cap must mark the result incomplete even when dedup collapses
    the enumerated tiles below it — otherwise a dirty set missing
    un-enumerated ranges would publish as exact and a subscriber would
    keep serving a stale tile forever."""
    z = 8
    # 5000 identical tiny envelopes (all one tile) + one far-away one
    # that the capped enumeration never reaches
    same = np.tile(np.array([[10.0, 10.0, 10.01, 10.01]]), (5000, 1))
    far = np.array([[120.0, -40.0, 120.01, -39.99]])
    envs = np.concatenate([same, far])
    addrs, count, capped = cdc.tiles_for_envelopes(z, envs, cap=4096)
    assert capped is True  # enumeration stopped early: incomplete
    # and uncapped, both regions are present
    addrs2, count2, capped2 = cdc.tiles_for_envelopes(z, envs)
    assert capped2 is False and count2 >= 2


def test_tile_cover_ranges_matches_bbox_intersects_brute():
    """The cover math vs the reference predicate, over adversarial
    envelopes: exact tile-edge touches, the anti-meridian seam, wraps,
    degenerate and polar rects."""
    from kart_tpu.ops.bbox import bbox_intersects_np
    from kart_tpu.tiles.grid import tile_cover_wsen

    envs = np.array(
        [
            (-180.0, -10.0, -170.0, 10.0),   # west seam touch
            (170.0, -10.0, 180.0, 10.0),     # east seam touch
            (0.0, 0.0, 45.0, 45.0),          # exact tile-edge corners
            (-45.0, -45.0, 0.0, 0.0),
            (175.0, -5.0, -175.0, 5.0),      # wrapping
            (10.0, 20.0, 20.0, 10.0),        # degenerate (n < s)
            (3.0, 86.0, 4.0, 89.0),          # beyond the mercator clamp
            (-3.0, -89.0, 3.0, -86.0),
            (7.5, 7.5, 7.5, 7.5),            # point
        ],
        dtype=np.float64,
    )
    for z in (0, 1, 2, 3, 4):
        n = 1 << z
        addrs, _count, _capped = cdc.tiles_for_envelopes(z, envs)
        got = {tuple(t) for t in addrs.tolist()}
        want = set()
        for x in range(n):
            for y in range(n):
                cover = tile_cover_wsen(z, x, y)
                if bbox_intersects_np(envs, np.asarray(cover)).any():
                    want.add((x, y))
        assert got == want, f"zoom {z}"


# ---------------------------------------------------------------------------
# the event log: sequences, resume, torn lines, rotation
# ---------------------------------------------------------------------------


def _event(seq, ref="refs/heads/main", new="b" * 40, old="a" * 40):
    return {"seq": seq, "ref": ref, "old": old, "new": new,
            "ts": 0.0, "cas_ts": 0.0, "dirty": None, "warm": None}


def test_event_log_append_since_and_reload(tmp_path):
    gitdir = str(tmp_path)
    log = EventLog(gitdir, max_events=100)
    assert log.head() == 0 and log.since(0) == ([], 0, None)
    for seq in (1, 2, 3):
        log.append_event(_event(seq, new=f"{seq:040x}"))
    events, head, reset = log.since(1)
    assert head == 3 and reset is None
    assert [e["seq"] for e in events] == [2, 3]
    assert log.tips() == {"refs/heads/main": f"{3:040x}"}
    # a fresh instance (a restarted server) reloads identically
    log2 = EventLog(gitdir, max_events=100)
    assert log2.head() == 3
    assert log2.tips() == log.tips()


def test_event_log_ignores_torn_trailing_line(tmp_path):
    log = EventLog(str(tmp_path), max_events=100)
    log.append_event(_event(1))
    log.append_event(_event(2, new="c" * 40))
    # a kill mid-append leaves a torn tail: that event was NOT announced
    with open(log.path, "ab") as f:
        f.write(b'{"seq": 3, "ref": "refs/heads/main", "new"')
    log2 = EventLog(str(tmp_path), max_events=100)
    assert log2.head() == 2
    assert log2.tips() == {"refs/heads/main": "c" * 40}


def test_event_log_retention_reset_marker_and_rotation(tmp_path):
    log = EventLog(str(tmp_path), max_events=5)
    for seq in range(1, 21):
        log.append_event(_event(seq, new=f"{seq:040x}"))
    events, head, reset = log.since(2)
    assert head == 20
    assert reset == log.oldest() - 1 and reset is not None
    assert [e["seq"] for e in events] == list(
        range(log.oldest(), 21)
    )
    # the file itself was rotated down (bounded on disk, not just memory)
    with open(log.path, "rb") as f:
        lines = [l for l in f.read().split(b"\n") if l.strip()]
    # rotation keeps the file bounded (≈2x the retention target between
    # rewrites), never the full 20-event history
    assert len(lines) < 16
    # deep-past resume on a fresh instance reports the same reset
    log2 = EventLog(str(tmp_path), max_events=5)
    _events2, head2, reset2 = log2.since(0)
    assert head2 == 20 and reset2 is not None


def test_emitter_books_announces_and_reconciles(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=6)
    emitter = events_mod.emitter_for(repo)
    # first boot adopts the existing tip silently
    assert emitter.log.head() == 0
    assert emitter.reconcile() == 0
    oid = edit_commit(
        repo, ds_path,
        updates=[{"fid": 1, "geom": None, "name": "x", "rating": 1.0}],
        message="e1",
    )
    assert emitter.reconcile() == 1
    wait_for(lambda: emitter.log.head() == 1, what="announce")
    events, head, _reset = emitter.events_since(0)
    assert events[0]["new"] == oid and events[0]["replay"] is True
    assert events[0]["dirty"][ds_path]["changed"] == {"updates": 1}
    # a restarted emitter over the same gitdir sees the announced state
    events_mod.drop_emitters(repo.gitdir)
    emitter2 = events_mod.emitter_for(repo)
    assert emitter2.log.head() == 1
    assert emitter2.reconcile() == 0


# ---------------------------------------------------------------------------
# the HTTP surface: long-poll, resume, SSE, stats block
# ---------------------------------------------------------------------------


def test_long_poll_fanout_resume_and_stats(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=8)
    server, url = serve_in_thread(repo)
    try:
        doc = get_json(f"{url}/api/v1/events")
        assert doc == {"events": [], "head": 0}
        results = {}

        def watcher():
            results["doc"] = get_json(f"{url}/api/v1/events?since=0&timeout=20")

        t = threading.Thread(target=watcher, daemon=True)
        t.start()
        time.sleep(0.3)  # the watcher is parked in its long poll
        oid = edit_commit(
            repo, ds_path,
            updates=[{"fid": 2, "geom": None, "name": "y", "rating": 2.0}],
            message="push-equivalent",
        )
        t.join(timeout=30)
        assert not t.is_alive()
        doc = results["doc"]
        assert doc["head"] == 1 and doc["events"][0]["new"] == oid
        assert doc["events"][0]["warm"] is not None
        # resume-by-sequence: since=1 blocks (nothing new), since=0 replays
        replay = get_json(f"{url}/api/v1/events?since=0&timeout=0")
        assert [e["seq"] for e in replay["events"]] == [1]
        empty = get_json(f"{url}/api/v1/events?since=1&timeout=0.2")
        assert empty["events"] == [] and empty["head"] == 1
        # the stats document gained the events block
        stats = get_json(f"{url}/api/v1/stats?format=json")
        ev = stats["events"]
        assert ev["head_seq"] == 1
        assert ev["watchers"] == 0
        assert ev["last_fanout_seconds"] is not None
    finally:
        server.shutdown()
        server.server_close()


def test_sse_stream_delivers_frames(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=6)
    server, url = serve_in_thread(repo)
    try:
        get_json(f"{url}/api/v1/events")  # create the emitter
        oid = edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "s", "rating": 3.0}],
            message="sse",
        )
        req = urllib.request.Request(
            f"{url}/api/v1/events?since=0&stream=sse"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            frame = b""
            while b"\n\n" not in frame:
                frame += resp.read(1)
        text = frame.decode()
        assert text.startswith("id: 1\n")
        event = json.loads(text.split("data: ", 1)[1].split("\n")[0])
        assert event["new"] == oid
    finally:
        server.shutdown()
        server.server_close()


def test_events_endpoint_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("KART_SERVE_EVENTS", "0")
    repo, _ds = make_imported_repo(tmp_path, n=4)
    server, url = serve_in_thread(repo)
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            get_json(f"{url}/api/v1/events")
        assert exc.value.code == 404
    finally:
        server.shutdown()
        server.server_close()


def test_warm_then_announce_pins_branch_tiles_to_old_tip(
    tmp_path, monkeypatch
):
    """While the warmer runs, branch-name tile requests serve the OLD
    commit (hot); after the announcement they serve the new tip — and the
    dirty tile is already warm in the cache."""
    repo, ds_path = make_imported_repo(tmp_path, n=8)
    old_tip = repo.refs.get("refs/heads/main")
    server, url = serve_in_thread(repo)
    try:
        get_json(f"{url}/api/v1/events")  # create the emitter
        release = threading.Event()
        real_warm = events_mod.warm_dirty_tiles

        def slow_warm(repo_, new_oid, summary, **kw):
            release.wait(20.0)
            return real_warm(repo_, new_oid, summary, **kw)

        monkeypatch.setattr(events_mod, "warm_dirty_tiles", slow_warm)
        new_tip = edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": gpoint(170.0, -40.0),
                      "name": "moved", "rating": 5.0}],
            message="warmed push",
        )
        emitter = events_mod.active_emitter(repo.gitdir)
        assert emitter.reconcile() == 1
        # mid-warm: the branch-name tile answers from the announced tip
        with urllib.request.urlopen(
            f"{url}/api/v1/tiles/main/{ds_path}/0/0/0", timeout=30
        ) as resp:
            header, _ = parse_payload(resp.read())
        assert header["commit"] == old_tip
        release.set()
        wait_for(lambda: emitter.log.head() == 1, what="announce")
        with urllib.request.urlopen(
            f"{url}/api/v1/tiles/main/{ds_path}/0/0/0", timeout=30
        ) as resp:
            header, _ = parse_payload(resp.read())
        assert header["commit"] == new_tip
        events, _h, _r = emitter.events_since(0)
        assert events[0]["warm"]["tiles"] > 0  # the dirty set was warmed
    finally:
        release.set()
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# restart / SIGKILL: missed-emission replay + resume-by-sequence
# ---------------------------------------------------------------------------


def test_restarted_server_replays_missed_emission(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=6)
    server, url = serve_in_thread(repo)
    try:
        get_json(f"{url}/api/v1/events")
        edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "a", "rating": 1.0}],
            message="seen",
        )
        doc = wait_for(
            lambda: get_json(f"{url}/api/v1/events?since=0&timeout=5"),
            what="first event",
        )
        assert doc["head"] == 1
    finally:
        server.shutdown()
        server.server_close()
    # the server "dies"; a push lands while nothing is running
    events_mod.drop_emitters(repo.gitdir)
    missed = edit_commit(
        repo, ds_path,
        updates=[{"fid": 2, "geom": None, "name": "b", "rating": 2.0}],
        message="missed while down",
    )
    server, url = serve_in_thread(repo)
    try:
        doc = get_json(f"{url}/api/v1/events?since=1&timeout=20")
        assert [e["seq"] for e in doc["events"]] == [2]
        assert doc["events"][0]["new"] == missed
        assert doc["events"][0]["replay"] is True
    finally:
        server.shutdown()
        server.server_close()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_long_poll_resume_across_server_sigkill(tmp_path):
    """The literal acceptance leg: a real `kart serve` subprocess is
    SIGKILLed mid-watch; a push lands while it is down; the restarted
    server replays the missed event to a watcher resuming by sequence."""
    repo, ds_path = make_imported_repo(tmp_path, n=6)
    workdir = repo.workdir or repo.gitdir
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    env = {
        **os.environ,
        "KART_REPO": str(workdir),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    }

    def spawn():
        proc = subprocess.Popen(
            [sys.executable, "-m", "kart_tpu.cli", "serve",
             "--host", "127.0.0.1", "--port", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        wait_for(
            lambda: _ping(f"{url}/api/v1/refs"), timeout=60, what="server up"
        )
        return proc

    def _ping(u):
        try:
            with urllib.request.urlopen(u, timeout=2):
                return True
        except OSError:
            return False

    proc = spawn()
    try:
        assert get_json(f"{url}/api/v1/events")["head"] == 0
        first = edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "k", "rating": 1.0}],
            message="before kill",
        )
        doc = get_json(f"{url}/api/v1/events?since=0&timeout=20")
        assert doc["events"][0]["new"] == first
        seen = doc["head"]
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        missed = edit_commit(
            repo, ds_path,
            updates=[{"fid": 2, "geom": None, "name": "m", "rating": 2.0}],
            message="while dead",
        )
        proc = spawn()
        doc = get_json(f"{url}/api/v1/events?since={seen}&timeout=20")
        assert [e["new"] for e in doc["events"]] == [missed]
        assert doc["events"][0]["seq"] == seen + 1
    finally:
        proc.kill()
        proc.wait()


# ---------------------------------------------------------------------------
# fleet: subscription beats the poll period; read-your-writes by sequence
# ---------------------------------------------------------------------------


def _raw_push(url, repo, new_oid, *, old_oid, client):
    from kart_tpu.transport.http import have_closure
    from kart_tpu.transport.protocol import ObjectEnumerator
    from kart_tpu.transport.remote import read_shallow

    info = client.ls_refs()
    server_refs = [o for o in info["heads"].values()]
    has = have_closure(repo.odb, server_refs, info.get("shallow", ()))
    enum = ObjectEnumerator(
        repo.odb, [new_oid], has=has.__contains__,
        sender_shallow=read_shallow(repo),
    )
    return client.receive_pack(
        enum,
        [{"ref": "refs/heads/main", "old": old_oid, "new": new_oid,
          "force": False}],
        shallow=lambda: enum.shallow_boundary,
    )


def test_subscribed_replica_lag_beats_poll_interval(tmp_path):
    """The fleet leg: with a 30s poll interval, a subscribed replica
    still converges in fan-out latency — the event stream, not the poll,
    drives replication."""
    from kart_tpu import fleet as fleet_mod

    (tmp_path / "p").mkdir()
    repo, ds_path = make_imported_repo(tmp_path / "p", n=8)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    server, url = serve_in_thread(repo)
    replica = KartRepo.init_repository(str(tmp_path / "r"))
    node = fleet_mod.FleetNode(replica, primary_url=url, poll_seconds=30.0)
    try:
        node.sync.sync_once()
        node.start()
        wait_for(node.sync.subscribed, what="subscription")
        oid = edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "lag", "rating": 1.0}],
            message="lag probe",
        )
        t0 = time.monotonic()
        wait_for(
            lambda: replica.refs.get("refs/heads/main") == oid,
            timeout=20, what="replica convergence",
        )
        lag = time.monotonic() - t0
        assert lag < 15.0  # decisively under the 30s poll interval
        wait_for(lambda: node.sync.applied_seq() >= 1, what="applied seq")
    finally:
        node.stop()
        server.shutdown()
        server.server_close()


def test_read_your_writes_by_sequence_through_replica(tmp_path):
    """A proxied push books its event sequence; the client pins reads on
    it and the subscribed replica satisfies the pin without an ancestry
    walk."""
    from kart_tpu import fleet as fleet_mod
    from kart_tpu.transport.retry import RetryPolicy

    (tmp_path / "p").mkdir()
    repo, ds_path = make_imported_repo(tmp_path / "p", n=8)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    p_server, p_url = serve_in_thread(repo)
    replica = KartRepo.init_repository(str(tmp_path / "r"))
    node = fleet_mod.FleetNode(replica, primary_url=p_url, poll_seconds=30.0)
    node.sync.sync_once()
    r_server, r_url = serve_in_thread(replica, fleet=node)
    try:
        node.start()
        wait_for(node.sync.subscribed, what="subscription")
        from kart_tpu import transport

        pusher = transport.clone(
            r_url, str(tmp_path / "c"), do_checkout=False
        )
        pusher.config.set_many(
            {"user.name": "t", "user.email": "t@t"}
        )
        old = pusher.refs.get("refs/heads/main")
        oid = edit_commit(
            pusher, ds_path,
            updates=[{"fid": 3, "geom": None, "name": "ryw", "rating": 9.0}],
            message="proxied",
        )
        client = HttpRemote(r_url, retry=RetryPolicy(attempts=2))
        payload = _raw_push(r_url, pusher, oid, old_oid=old, client=client)
        assert isinstance(payload.get("event_seq"), int)
        assert client._min_seq == payload["event_seq"]
        # the pinned read answers with the pushed tip (stall, not stale)
        info = client.ls_refs()
        assert info["heads"]["main"] == oid
        assert node.sync.applied_seq() >= payload["event_seq"]
    finally:
        node.stop()
        r_server.shutdown()
        r_server.server_close()
        p_server.shutdown()
        p_server.server_close()


# ---------------------------------------------------------------------------
# CLI: kart watch / kart top
# ---------------------------------------------------------------------------


def test_kart_watch_streams_json_lines(tmp_path, cli_runner):
    from kart_tpu.cli import cli

    repo, ds_path = make_imported_repo(tmp_path, n=6)
    server, url = serve_in_thread(repo)
    try:
        get_json(f"{url}/api/v1/events")
        oid = edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "w", "rating": 1.0}],
            message="watched",
        )
        emitter = events_mod.active_emitter(repo.gitdir)
        emitter.reconcile()
        wait_for(lambda: emitter.log.head() == 1, what="announce")
        result = cli_runner.invoke(
            cli, ["watch", url, "--since", "0", "-n", "1"]
        )
        assert result.exit_code == 0, result.output
        event = json.loads(result.output.strip().splitlines()[-1])
        assert event["new"] == oid and event["seq"] == 1
        # dataset filter: a non-matching filter prints nothing and times out
        result = cli_runner.invoke(
            cli, ["watch", url, "--since", "0", "--dataset", "nope",
                  "--timeout", "0.5"]
        )
        assert result.exit_code == 0
        assert result.output.strip() == ""
    finally:
        server.shutdown()
        server.server_close()


def test_kart_top_renders_events_block(tmp_path, cli_runner):
    from kart_tpu.cli import cli

    repo, ds_path = make_imported_repo(tmp_path, n=6)
    server, url = serve_in_thread(repo)
    try:
        get_json(f"{url}/api/v1/events")  # emitter exists -> stats block
        result = cli_runner.invoke(cli, ["top", url, "--once"])
        assert result.exit_code == 0, result.output
        assert "events  watchers" in result.output
        assert "head seq" in result.output
    finally:
        server.shutdown()
        server.server_close()


def test_stdio_events_op(tmp_path, monkeypatch):
    from test_ssh_transport import _install_fake_ssh

    from kart_tpu.transport.stdio import StdioRemote

    _install_fake_ssh(tmp_path, monkeypatch)
    repo, ds_path = make_imported_repo(tmp_path, n=5)
    remote = StdioRemote(f"ssh://localhost{repo.workdir or repo.gitdir}")
    try:
        # the handshake adopts the current tip (first boot, head 0); the
        # edit lands afterwards, so the next poll reconciles + announces
        assert remote.events()["head"] == 0
        oid = edit_commit(
            repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": "ssh", "rating": 1.0}],
            message="over ssh",
        )
        doc = remote.events(0, timeout=15.0)
        assert doc["head"] == 1
        assert doc["events"][-1]["new"] == oid
    finally:
        remote.close()
