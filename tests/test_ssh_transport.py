"""SSH/stdio transport: clone/push/pull/promisor against a pipe-spawned
remote process (`kart serve-stdio`), exactly the two-process shape a real
``ssh host kart serve-stdio`` runs — only the ssh binary is a stub that
execs the command locally."""

import os
import stat
import subprocess
import sys

import pytest

from helpers import edit_commit, make_imported_repo
from kart_tpu.transport.stdio import StdioRemote, is_ssh_url, parse_ssh_url


def _install_fake_ssh(tmp_path, monkeypatch):
    """A fake `ssh` that drops the host argument and runs the command
    locally, plus a `kart` shim on PATH so the spawned command resolves —
    the full spawn path (argv building, quoting, pipes) stays real."""
    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    kart = bindir / "kart"
    kart.write_text(
        "#!/bin/sh\n"
        f'PYTHONPATH={os.path.dirname(os.path.dirname(os.path.abspath(__file__)))} '
        f'exec {sys.executable} -m kart_tpu.cli "$@"\n'
    )
    kart.chmod(kart.stat().st_mode | stat.S_IEXEC)
    fake_ssh = bindir / "fake-ssh"
    fake_ssh.write_text(
        "#!/bin/sh\n"
        "# $1 = [user@]host (ignored), rest = the remote command string\n"
        "shift\n"
        'exec sh -c "$*"\n'
    )
    fake_ssh.chmod(fake_ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("KART_SSH", str(fake_ssh))
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")


def test_url_parsing():
    assert parse_ssh_url("ssh://alice@host:2222/srv/repo") == (
        "alice@host",
        "2222",
        "/srv/repo",
    )
    assert parse_ssh_url("ssh://host/srv/repo") == ("host", None, "/srv/repo")
    assert parse_ssh_url("alice@host:repos/x") == ("alice@host", None, "repos/x")
    assert parse_ssh_url("host:/abs/path") == ("host", None, "/abs/path")
    assert parse_ssh_url("/local/path") is None
    assert parse_ssh_url("./rel:path") is None
    assert parse_ssh_url("http://h/x") is None
    assert parse_ssh_url("c:/windows/style") is None
    assert is_ssh_url("host:/x") and not is_ssh_url("/x")


@pytest.fixture()
def ssh_remote_repo(tmp_path, monkeypatch):
    """A served repo + the ssh URL that reaches it through the stub."""
    _install_fake_ssh(tmp_path, monkeypatch)
    (tmp_path / "server").mkdir()
    repo, ds_path = make_imported_repo(tmp_path / "server", n=12)
    repo.config["receive.denyCurrentBranch"] = "ignore"
    url = f"testhost:{repo.workdir or repo.gitdir}"
    return repo, ds_path, url


def test_ls_refs_over_pipe(ssh_remote_repo):
    repo, _, url = ssh_remote_repo
    client = StdioRemote(url)
    try:
        info = client.ls_refs()
        assert info["heads"]["main"] == repo.head_commit_oid
        assert info["head_branch"] == "main"
        # second call reuses the same connection
        assert client.ls_refs()["heads"] == info["heads"]
    finally:
        client.close()


def test_clone_pull_push_roundtrip(tmp_path, ssh_remote_repo):
    server_repo, ds_path, url = ssh_remote_repo
    from kart_tpu.transport.remote import clone, fetch, push

    local = clone(url, str(tmp_path / "local"), do_checkout=False)
    assert local.head_commit_oid == server_repo.head_commit_oid
    assert local.config.get("remote.origin.url") == url

    # server advances; pull sees it
    edit_commit(
        server_repo, ds_path,
        updates=[{"fid": 2, "geom": None, "name": "upstream", "rating": 0.1}],
    )
    updated = fetch(local, "origin")
    assert updated["refs/remotes/origin/main"] == server_repo.head_commit_oid

    # local commit pushes back (on a side branch so CAS + ref creation both
    # exercise)
    local.refs.set("refs/heads/feature", local.head_commit_oid, log_message="b")
    local.refs.set_head("refs/heads/feature", log_message="switch")
    edit_commit(
        local, ds_path,
        updates=[{"fid": 3, "geom": None, "name": "local", "rating": 0.2}],
    )
    result = push(local, "origin", ["feature:feature"])
    assert result["refs/heads/feature"] == local.head_commit_oid
    assert server_repo.refs.get("refs/heads/feature") == local.head_commit_oid

    # delete over the wire
    result = push(local, "origin", [":feature"])
    assert result["refs/heads/feature"] is None
    assert server_repo.refs.get("refs/heads/feature") is None


def test_diverged_push_rebased_or_rejected_over_ssh(tmp_path, ssh_remote_repo):
    """The contended-write contract over the stdio/ssh transport: disjoint
    divergence auto-rebases server-side; a real conflict comes back as one
    terminal structured rejection (same wire semantics as HTTP,
    docs/SERVING.md §6); --force still overrides."""
    server_repo, ds_path, url = ssh_remote_repo
    from kart_tpu.transport.remote import RemoteError, clone, push

    local = clone(url, str(tmp_path / "local"), do_checkout=False)
    # server moves ahead; local histories diverge on DIFFERENT features:
    # the server merges instead of bouncing the push
    upstream = edit_commit(
        server_repo, ds_path,
        updates=[{"fid": 4, "geom": None, "name": "srv", "rating": 1.0}],
    )
    local_oid = edit_commit(
        local, ds_path,
        updates=[{"fid": 5, "geom": None, "name": "loc", "rating": 2.0}],
    )
    updated = push(local, "origin", ["main:main"])
    tip = server_repo.refs.get("refs/heads/main")
    assert updated == {"refs/heads/main": tip}
    assert server_repo.odb.read_commit(tip).parents == (upstream, local_oid)

    # now diverge on the SAME feature: a genuine conflict, terminal report
    edit_commit(
        server_repo, ds_path,
        updates=[{"fid": 7, "geom": None, "name": "srv7", "rating": 1.0}],
    )
    edit_commit(
        local, ds_path,
        updates=[{"fid": 7, "geom": None, "name": "loc7", "rating": 2.0}],
    )
    with pytest.raises(RemoteError, match="conflict"):
        push(local, "origin", ["main:main"])
    # force push wins
    push(local, "origin", ["main:main"], force=True)
    assert server_repo.refs.get("refs/heads/main") == local.head_commit_oid


def test_spatial_filtered_clone_and_promisor_backfill(tmp_path, ssh_remote_repo):
    """Filtered partial clone over the pipe: the filter runs on the serving
    side; later reads of out-of-filter features backfill through the same
    ssh transport (promisor semantics)."""
    server_repo, ds_path, url = ssh_remote_repo
    from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec
    from kart_tpu.transport.remote import clone

    # points sit at x = 100 + fid; keep only fids <= 4
    spec = ResolvedSpatialFilterSpec(
        "EPSG:4326", "POLYGON((100 -45, 104.5 -45, 104.5 -39, 100 -39, 100 -45))"
    )
    local = clone(
        url,
        str(tmp_path / "filtered"),
        do_checkout=False,
        spatial_filter_spec=spec,
    )
    assert local.config.get_bool("remote.origin.promisor")
    ds = local.datasets("HEAD")[ds_path]
    in_filter = ds.get_feature([2])
    assert in_filter["name"] == "feature-2"

    from kart_tpu.core.odb import ObjectPromised

    tree = ds.feature_tree
    blob_oids = [e.oid for _, e in tree.walk_blobs()]
    missing = [o for o in blob_oids if not local.odb.contains(o)]
    assert missing, "filtered clone should omit out-of-filter blobs"

    # on-demand backfill over the same ssh transport
    from kart_tpu.transport.remote import fetch_promised_blobs

    fetched = fetch_promised_blobs(local, missing)
    assert fetched == len(missing)
    far = ds.get_feature([11])
    assert far["name"] == "feature-11"


def test_shallow_clone_over_pipe(tmp_path, ssh_remote_repo):
    server_repo, ds_path, url = ssh_remote_repo
    for i in range(3):
        edit_commit(
            server_repo, ds_path,
            updates=[{"fid": 1, "geom": None, "name": f"v{i}", "rating": float(i)}],
        )
    from kart_tpu.transport.remote import clone, read_shallow

    local = clone(url, str(tmp_path / "shallow"), do_checkout=False, depth=1)
    assert local.head_commit_oid == server_repo.head_commit_oid
    assert read_shallow(local) == {server_repo.head_commit_oid}


def test_cli_clone_and_push_via_ssh_url(tmp_path, ssh_remote_repo, cli_runner):
    """The CLI end of it: `kart clone user@host:path` works."""
    from kart_tpu.cli import cli

    server_repo, ds_path, url = ssh_remote_repo
    dest = str(tmp_path / "cli-clone")
    result = cli_runner.invoke(
        cli, ["clone", url, dest, "--no-checkout"], catch_exceptions=False
    )
    assert result.exit_code == 0, result.output
    result = cli_runner.invoke(
        cli, ["-C", dest, "log", "--oneline"], catch_exceptions=False
    )
    assert result.exit_code == 0, result.output
    assert "Import 1 dataset" in result.output


def test_server_rejects_bad_ref_name(ssh_remote_repo):
    """The shared receive-pack validation runs on the stdio path too."""
    from kart_tpu.transport.stdio import StdioRemote, StdioTransportError

    _, _, url = ssh_remote_repo
    client = StdioRemote(url)
    try:
        with pytest.raises(StdioTransportError, match="[Rr]ef"):
            client.receive_pack(
                [], [{"ref": "config", "old": None, "new": "0" * 40, "force": True}]
            )
    finally:
        client.close()


def test_ssh_url_option_injection_rejected():
    """Hostnames/paths beginning with '-' must not parse (they would reach
    ssh as options — the CVE-2017-1000117 class)."""
    assert parse_ssh_url("-oProxyCommand=payload:x") is None
    assert parse_ssh_url("ssh://-oProxyCommand=payload/p") is None
    assert parse_ssh_url("host:-path") is None
    # IPv6 forms parse correctly
    assert parse_ssh_url("ssh://[::1]/srv/repo") == ("::1", None, "/srv/repo")
    assert parse_ssh_url("ssh://u@[::1]:2222/srv/repo") == ("u@::1", "2222", "/srv/repo")


def test_server_error_keeps_connection_usable(ssh_remote_repo):
    """An op-level failure returns an error frame; the next request on the
    same connection still works (HTTP-500 equivalence)."""
    from kart_tpu.transport.stdio import StdioRemote, StdioTransportError

    repo, _, url = ssh_remote_repo
    client = StdioRemote(url)
    try:
        with pytest.raises(StdioTransportError, match="error"):
            client.fetch_pack(repo, [repo.head_commit_oid], filter_spec="not-a-rect")
        # connection survives
        assert client.ls_refs()["heads"]["main"] == repo.head_commit_oid
    finally:
        client.close()


def test_serve_stdio_rejects_enclosed_nonrepo_path(tmp_path, ssh_remote_repo):
    """Serving a non-repo subdirectory must error, not serve the enclosing
    repo."""
    server_repo, _, _ = ssh_remote_repo
    sub = os.path.join(server_repo.workdir, "subdir")
    os.makedirs(sub, exist_ok=True)
    from kart_tpu.transport.stdio import StdioRemote, StdioTransportError

    client = StdioRemote(f"testhost:{sub}")
    try:
        with pytest.raises(StdioTransportError):
            client.ls_refs()
    finally:
        client.close()


def test_parse_ssh_url_rejects_non_numeric_port():
    """The port rides ssh's argv after '-p': digits only (ADVICE r3)."""
    assert parse_ssh_url("ssh://host:22x/srv/repo") is None
    assert parse_ssh_url("ssh://host:22 -oProxyCommand=evil/srv/repo") is None
    assert parse_ssh_url("ssh://[::1]:bad/srv/repo") is None
    assert parse_ssh_url("ssh://host:2222/srv/repo") == (
        "host",
        "2222",
        "/srv/repo",
    )
