import subprocess

import pytest

from kart_tpu.core.objects import (
    Commit,
    Signature,
    TreeEntry,
    MODE_BLOB,
    MODE_TREE,
    hash_blob,
    parse_tree,
    serialise_tree,
)


def test_blob_hash_matches_git():
    # known-answer: git hash-object of b"hello\n"
    assert hash_blob(b"hello\n") == "ce013625030ba8dba906f756967f9e9ca394464a"
    # empty blob
    assert hash_blob(b"") == "e69de29bb2d1d6434b8b29ae775ad8c2e48c5391"


def test_tree_roundtrip():
    entries = [
        TreeEntry("zeta", MODE_BLOB, "ce013625030ba8dba906f756967f9e9ca394464a"),
        TreeEntry("alpha", MODE_TREE, "4b825dc642cb6eb9a060e54bf8d69288fbee4904"),
        TreeEntry("beta", MODE_BLOB, "e69de29bb2d1d6434b8b29ae775ad8c2e48c5391"),
    ]
    data = serialise_tree(entries)
    parsed = parse_tree(data)
    assert [e.name for e in parsed] == ["alpha", "beta", "zeta"]
    assert parsed[0].is_tree


def test_tree_git_sort_order():
    # git sorts trees as if their name had a trailing slash: "a.b" < "a/" -> "a" tree sorts after "a.b"
    entries = [
        TreeEntry("a", MODE_TREE, "4b825dc642cb6eb9a060e54bf8d69288fbee4904"),
        TreeEntry("a.b", MODE_BLOB, "e69de29bb2d1d6434b8b29ae775ad8c2e48c5391"),
    ]
    parsed = parse_tree(serialise_tree(entries))
    assert [e.name for e in parsed] == ["a.b", "a"]


def test_signature_roundtrip():
    sig = Signature("Test User", "test@example.com", 1700000000, -330)
    assert Signature.parse(sig.format()) == sig
    sig2 = Signature("X", "x@y", 1700000000, 765)
    assert Signature.parse(sig2.format()) == sig2


def test_commit_roundtrip():
    sig = Signature("A", "a@b.c", 1700000000, 0)
    c = Commit(
        tree="4b825dc642cb6eb9a060e54bf8d69288fbee4904",
        parents=("ce013625030ba8dba906f756967f9e9ca394464a",),
        author=sig,
        committer=sig,
        message="hello world\n\nbody\n",
    )
    assert Commit.parse(c.serialise()) == c
    assert c.message_summary == "hello world"
