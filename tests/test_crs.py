import numpy as np
import pytest

from kart_tpu.crs import (
    CRS,
    NZTM_WKT,
    WGS84_WKT,
    Transform,
    get_identifier_int,
    get_identifier_str,
    make_crs,
    normalise_wkt,
    parse_name,
)


def test_parse_wgs84():
    crs = make_crs("EPSG:4326")
    assert crs.is_geographic
    assert crs.authority == "EPSG"
    assert crs.code == "4326"
    assert parse_name(crs.wkt) == "WGS 84"
    assert get_identifier_str(crs.wkt) == "EPSG:4326"
    assert get_identifier_int(crs.wkt) == 4326


def test_parse_nztm():
    crs = CRS(NZTM_WKT)
    assert crs.is_projected
    assert crs.projection == "Transverse_Mercator"
    assert crs.params["central_meridian"] == 173.0
    assert crs.identifier_int == 2193


def test_normalise_wkt_stable():
    n1 = normalise_wkt(WGS84_WKT)
    assert normalise_wkt(n1) == n1


def test_nztm_known_point():
    # The projection origin maps to (false_easting, false_northing).
    t = Transform("EPSG:4326", "EPSG:2193")
    x, y = t.transform(np.array([173.0]), np.array([0.0]))
    assert abs(x[0] - 1600000.0) < 1e-3
    assert abs(y[0] - 10000000.0) < 1e-3

    # Wellington (EPSG registry test point accuracy ~1mm for Krueger series)
    x, y = t.transform(np.array([174.7772239]), np.array([-41.2887639]))
    assert abs(x[0] - 1748795.0) < 200.0  # sanity envelope
    assert abs(y[0] - 5427717.0) < 200.0


def test_tm_roundtrip():
    t = Transform("EPSG:4326", "EPSG:2193")
    inv = Transform("EPSG:2193", "EPSG:4326")
    lons = np.linspace(166.0, 179.0, 20)
    lats = np.linspace(-47.0, -34.0, 20)
    x, y = t.transform(lons, lats)
    lon2, lat2 = inv.transform(x, y)
    np.testing.assert_allclose(lon2, lons, atol=1e-9)
    np.testing.assert_allclose(lat2, lats, atol=1e-9)


def test_web_mercator():
    t = Transform("EPSG:4326", "EPSG:3857")
    x, y = t.transform(np.array([1.0]), np.array([0.0]))
    assert abs(x[0] - 111319.49079327358) < 1e-6
    assert abs(y[0]) < 1e-6


def test_identity_transform():
    t = Transform("EPSG:4326", "EPSG:4326")
    assert t.is_identity
    xs, ys = t.transform(np.array([1.0]), np.array([2.0]))
    assert xs[0] == 1.0 and ys[0] == 2.0


def test_transform_envelope():
    t = Transform("EPSG:2193", "EPSG:4326")
    env = t.transform_envelope((1500000, 1700000, 5300000, 5500000))
    # roughly central New Zealand
    assert 171 < env[0] < env[1] < 176
    assert -43 < env[2] < env[3] < -40


def test_utm():
    crs = make_crs("EPSG:32760")  # UTM 60S
    assert crs.is_projected
    t = Transform("EPSG:4326", crs)
    x, y = t.transform(np.array([177.0]), np.array([0.0]))
    assert abs(x[0] - 500000.0) < 1e-3
    assert abs(y[0] - 10000000.0) < 1e-3


LCC_2SP_CLARKE = (
    'PROJCS["test LCC",GEOGCS["NAD27",DATUM["North_American_Datum_1927",'
    'SPHEROID["Clarke 1866",6378206.4,294.978698213898]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic_2SP"],'
    'PARAMETER["standard_parallel_1",33],PARAMETER["standard_parallel_2",45],'
    'PARAMETER["latitude_of_origin",23],PARAMETER["central_meridian",-96],'
    'PARAMETER["false_easting",0],PARAMETER["false_northing",0],UNIT["metre",1]]'
)
NAD27_GEO = (
    'GEOGCS["NAD27",DATUM["North_American_Datum_1927",'
    'SPHEROID["Clarke 1866",6378206.4,294.978698213898]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]]'
)
LAMBERT_93 = (
    'PROJCS["RGF93 / Lambert-93",GEOGCS["RGF93",'
    'DATUM["Reseau_Geodesique_Francais_1993",'
    'SPHEROID["GRS 1980",6378137,298.257222101]],PRIMEM["Greenwich",0],'
    'UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic_2SP"],'
    'PARAMETER["standard_parallel_1",49],PARAMETER["standard_parallel_2",44],'
    'PARAMETER["latitude_of_origin",46.5],PARAMETER["central_meridian",3],'
    'PARAMETER["false_easting",700000],PARAMETER["false_northing",6600000],'
    'UNIT["metre",1],AUTHORITY["EPSG","2154"]]'
)


def test_lcc_2sp_snyder_known_answer():
    """Snyder (1987) p.296 numerical example for LCC 2SP on Clarke 1866."""
    t = Transform(NAD27_GEO, LCC_2SP_CLARKE)
    x, y = t.transform(np.array([-75.0]), np.array([35.0]))
    assert abs(x[0] - 1894410.9) < 1.0
    assert abs(y[0] - 1564649.5) < 1.0
    inv = Transform(LCC_2SP_CLARKE, NAD27_GEO)
    lon, lat = inv.transform(x, y)
    assert abs(lon[0] + 75.0) < 1e-7
    assert abs(lat[0] - 35.0) < 1e-7


def test_lcc_lambert93_paris():
    t = Transform(
        'GEOGCS["RGF93",DATUM["Reseau_Geodesique_Francais_1993",'
        'SPHEROID["GRS 1980",6378137,298.257222101]],PRIMEM["Greenwich",0],'
        'UNIT["degree",0.0174532925199433]]',
        LAMBERT_93,
    )
    x, y = t.transform(np.array([2.3522]), np.array([48.8566]))
    assert abs(x[0] - 652470) < 100
    assert abs(y[0] - 6862035) < 100


def test_lcc_envelope_roundtrip():
    t = Transform(LAMBERT_93, "EPSG:4326")
    env = t.transform_envelope((600000, 800000, 6700000, 6900000))
    assert 0.5 < env[0] < env[1] < 5.0
    assert 47.0 < env[2] < env[3] < 50.0


OSGB36_GEO = (
    'GEOGCS["OSGB 1936",DATUM["OSGB_1936",'
    'SPHEROID["Airy 1830",6377563.396,299.3249646],'
    'TOWGS84[446.448,-125.157,542.06,0.15,0.247,0.842,-20.489]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433],'
    'AUTHORITY["EPSG","4277"]]'
)
WGS84_GEO = (
    'GEOGCS["WGS 84",DATUM["WGS_1984",'
    'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
    'UNIT["degree",0.0174532925199433],AUTHORITY["EPSG","4326"]]'
)


def test_towgs84_datum_shift():
    """7-parameter Helmert (EPSG 9606) applied between datums: WGS84 ->
    OSGB36 with the standard TOWGS84 moves a UK point by the published
    ~100m, matching the OS Net example to single-transformation accuracy."""
    t = Transform(WGS84_GEO, OSGB36_GEO)
    lon, lat = t.transform(np.array([1.716073973]), np.array([52.658007833]))
    assert abs(lon[0] - 1.7179229) < 5e-5   # ~+124m east
    assert abs(lat[0] - 52.6575687) < 5e-5  # ~-49m south
    # exact roundtrip (the method is sign-reversible)
    inv = Transform(OSGB36_GEO, WGS84_GEO)
    lon2, lat2 = inv.transform(lon, lat)
    assert abs(lon2[0] - 1.716073973) < 1e-7
    assert abs(lat2[0] - 52.658007833) < 1e-7


def test_no_towgs84_means_wgs84_equivalent():
    """Datums without a declared shift keep the old behavior: treated as
    WGS84-equivalent (modern datums are within ~1m)."""
    t = Transform("EPSG:4167", "EPSG:4326")  # NZGD2000 (no TOWGS84) -> WGS84
    lon, lat = t.transform(np.array([173.0]), np.array([-41.0]))
    assert lon[0] == 173.0 and lat[0] == -41.0


def test_mercator_1sp_honours_central_meridian():
    """EPSG:3832 (PDC Mercator, central_meridian 150): lon 180 maps 30deg
    east of the projection origin — the round-1 implementation ignored the
    central meridian, shifting the Pacific by 150 degrees."""
    wkt_3832 = (
        'PROJCS["WGS 84 / PDC Mercator",GEOGCS["WGS 84",DATUM["WGS_1984",'
        'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
        'UNIT["degree",0.0174532925199433]],PROJECTION["Mercator_1SP"],'
        'PARAMETER["central_meridian",150],PARAMETER["scale_factor",1],'
        'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
        'UNIT["metre",1],AUTHORITY["EPSG","3832"]]'
    )
    t = Transform("EPSG:4326", wkt_3832)
    x, y = t.transform(np.array([180.0]), np.array([0.0]))
    assert abs(x[0] - 6378137 * np.radians(30.0)) < 1.0
    assert abs(y[0]) < 1e-6
    inv = Transform(wkt_3832, "EPSG:4326")
    lon, lat = inv.transform(x, y)
    assert abs(lon[0] - 180.0) < 1e-9 and abs(lat[0]) < 1e-9


def test_mercator_ellipsoidal_vs_web_spherical():
    """Mercator_1SP on WGS84 is ellipsoidal; EPSG:3857 stays spherical
    despite its WKT claiming Mercator_1SP. At lat 45 they differ by ~30km
    in northing."""
    wkt_merc = (
        'PROJCS["World Mercator",GEOGCS["WGS 84",DATUM["WGS_1984",'
        'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
        'UNIT["degree",0.0174532925199433]],PROJECTION["Mercator_1SP"],'
        'PARAMETER["central_meridian",0],PARAMETER["scale_factor",1],'
        'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
        'UNIT["metre",1],AUTHORITY["EPSG","3395"]]'
    )
    t_ell = Transform("EPSG:4326", wkt_merc)
    t_sph = Transform("EPSG:4326", "EPSG:3857")
    _, y_ell = t_ell.transform(np.array([0.0]), np.array([45.0]))
    _, y_sph = t_sph.transform(np.array([0.0]), np.array([45.0]))
    # EPSG:3395 at lat 45: 5591295.92m (published); 3857: 5621521.49m
    assert abs(y_ell[0] - 5591295.92) < 1.0
    assert abs(y_sph[0] - 5621521.49) < 1.0


LCC_SPHERE = (
    'PROJCS["test LCC sphere",GEOGCS["sphere",DATUM["sphere",'
    'SPHEROID["sphere",6370997,0]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic_2SP"],'
    'PARAMETER["standard_parallel_1",33],PARAMETER["standard_parallel_2",45],'
    'PARAMETER["latitude_of_origin",23],PARAMETER["central_meridian",-96],'
    'PARAMETER["false_easting",0],PARAMETER["false_northing",0],UNIT["metre",1]]'
)
SPHERE_GEO = (
    'GEOGCS["sphere",DATUM["sphere",SPHEROID["sphere",6370997,0]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]]'
)


def test_lcc_spherical_ellipsoid_no_crash():
    """LCC on SPHEROID[...,0] (a sphere) raised ZeroDivisionError before the
    r2 advisor fix; it must behave like the e=0 degenerate case and
    round-trip cleanly."""
    t = Transform(SPHERE_GEO, LCC_SPHERE)
    x, y = t.transform(np.array([-75.0]), np.array([35.0]))
    assert np.all(np.isfinite(x)) and np.all(np.isfinite(y))
    inv = Transform(LCC_SPHERE, SPHERE_GEO)
    lon, lat = inv.transform(x, y)
    assert abs(lon[0] + 75.0) < 1e-7
    assert abs(lat[0] - 35.0) < 1e-7
