import numpy as np
import pytest

from kart_tpu.crs import (
    CRS,
    NZTM_WKT,
    WGS84_WKT,
    Transform,
    get_identifier_int,
    get_identifier_str,
    make_crs,
    normalise_wkt,
    parse_name,
)


def test_parse_wgs84():
    crs = make_crs("EPSG:4326")
    assert crs.is_geographic
    assert crs.authority == "EPSG"
    assert crs.code == "4326"
    assert parse_name(crs.wkt) == "WGS 84"
    assert get_identifier_str(crs.wkt) == "EPSG:4326"
    assert get_identifier_int(crs.wkt) == 4326


def test_parse_nztm():
    crs = CRS(NZTM_WKT)
    assert crs.is_projected
    assert crs.projection == "Transverse_Mercator"
    assert crs.params["central_meridian"] == 173.0
    assert crs.identifier_int == 2193


def test_normalise_wkt_stable():
    n1 = normalise_wkt(WGS84_WKT)
    assert normalise_wkt(n1) == n1


def test_nztm_known_point():
    # The projection origin maps to (false_easting, false_northing).
    t = Transform("EPSG:4326", "EPSG:2193")
    x, y = t.transform(np.array([173.0]), np.array([0.0]))
    assert abs(x[0] - 1600000.0) < 1e-3
    assert abs(y[0] - 10000000.0) < 1e-3

    # Wellington (EPSG registry test point accuracy ~1mm for Krueger series)
    x, y = t.transform(np.array([174.7772239]), np.array([-41.2887639]))
    assert abs(x[0] - 1748795.0) < 200.0  # sanity envelope
    assert abs(y[0] - 5427717.0) < 200.0


def test_tm_roundtrip():
    t = Transform("EPSG:4326", "EPSG:2193")
    inv = Transform("EPSG:2193", "EPSG:4326")
    lons = np.linspace(166.0, 179.0, 20)
    lats = np.linspace(-47.0, -34.0, 20)
    x, y = t.transform(lons, lats)
    lon2, lat2 = inv.transform(x, y)
    np.testing.assert_allclose(lon2, lons, atol=1e-9)
    np.testing.assert_allclose(lat2, lats, atol=1e-9)


def test_web_mercator():
    t = Transform("EPSG:4326", "EPSG:3857")
    x, y = t.transform(np.array([1.0]), np.array([0.0]))
    assert abs(x[0] - 111319.49079327358) < 1e-6
    assert abs(y[0]) < 1e-6


def test_identity_transform():
    t = Transform("EPSG:4326", "EPSG:4326")
    assert t.is_identity
    xs, ys = t.transform(np.array([1.0]), np.array([2.0]))
    assert xs[0] == 1.0 and ys[0] == 2.0


def test_transform_envelope():
    t = Transform("EPSG:2193", "EPSG:4326")
    env = t.transform_envelope((1500000, 1700000, 5300000, 5500000))
    # roughly central New Zealand
    assert 171 < env[0] < env[1] < 176
    assert -43 < env[2] < env[3] < -40


def test_utm():
    crs = make_crs("EPSG:32760")  # UTM 60S
    assert crs.is_projected
    t = Transform("EPSG:4326", crs)
    x, y = t.transform(np.array([177.0]), np.array([0.0]))
    assert abs(x[0] - 500000.0) < 1e-3
    assert abs(y[0] - 10000000.0) < 1e-3


LCC_2SP_CLARKE = (
    'PROJCS["test LCC",GEOGCS["NAD27",DATUM["North_American_Datum_1927",'
    'SPHEROID["Clarke 1866",6378206.4,294.978698213898]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic_2SP"],'
    'PARAMETER["standard_parallel_1",33],PARAMETER["standard_parallel_2",45],'
    'PARAMETER["latitude_of_origin",23],PARAMETER["central_meridian",-96],'
    'PARAMETER["false_easting",0],PARAMETER["false_northing",0],UNIT["metre",1]]'
)
NAD27_GEO = (
    'GEOGCS["NAD27",DATUM["North_American_Datum_1927",'
    'SPHEROID["Clarke 1866",6378206.4,294.978698213898]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]]'
)
LAMBERT_93 = (
    'PROJCS["RGF93 / Lambert-93",GEOGCS["RGF93",'
    'DATUM["Reseau_Geodesique_Francais_1993",'
    'SPHEROID["GRS 1980",6378137,298.257222101]],PRIMEM["Greenwich",0],'
    'UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic_2SP"],'
    'PARAMETER["standard_parallel_1",49],PARAMETER["standard_parallel_2",44],'
    'PARAMETER["latitude_of_origin",46.5],PARAMETER["central_meridian",3],'
    'PARAMETER["false_easting",700000],PARAMETER["false_northing",6600000],'
    'UNIT["metre",1],AUTHORITY["EPSG","2154"]]'
)


def test_lcc_2sp_snyder_known_answer():
    """Snyder (1987) p.296 numerical example for LCC 2SP on Clarke 1866."""
    t = Transform(NAD27_GEO, LCC_2SP_CLARKE)
    x, y = t.transform(np.array([-75.0]), np.array([35.0]))
    assert abs(x[0] - 1894410.9) < 1.0
    assert abs(y[0] - 1564649.5) < 1.0
    inv = Transform(LCC_2SP_CLARKE, NAD27_GEO)
    lon, lat = inv.transform(x, y)
    assert abs(lon[0] + 75.0) < 1e-7
    assert abs(lat[0] - 35.0) < 1e-7


def test_lcc_lambert93_paris():
    t = Transform(
        'GEOGCS["RGF93",DATUM["Reseau_Geodesique_Francais_1993",'
        'SPHEROID["GRS 1980",6378137,298.257222101]],PRIMEM["Greenwich",0],'
        'UNIT["degree",0.0174532925199433]]',
        LAMBERT_93,
    )
    x, y = t.transform(np.array([2.3522]), np.array([48.8566]))
    assert abs(x[0] - 652470) < 100
    assert abs(y[0] - 6862035) < 100


def test_lcc_envelope_roundtrip():
    t = Transform(LAMBERT_93, "EPSG:4326")
    env = t.transform_envelope((600000, 800000, 6700000, 6900000))
    assert 0.5 < env[0] < env[1] < 5.0
    assert 47.0 < env[2] < env[3] < 50.0


OSGB36_GEO = (
    'GEOGCS["OSGB 1936",DATUM["OSGB_1936",'
    'SPHEROID["Airy 1830",6377563.396,299.3249646],'
    'TOWGS84[446.448,-125.157,542.06,0.15,0.247,0.842,-20.489]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433],'
    'AUTHORITY["EPSG","4277"]]'
)
WGS84_GEO = (
    'GEOGCS["WGS 84",DATUM["WGS_1984",'
    'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
    'UNIT["degree",0.0174532925199433],AUTHORITY["EPSG","4326"]]'
)


def test_towgs84_datum_shift():
    """7-parameter Helmert (EPSG 9606) applied between datums: WGS84 ->
    OSGB36 with the standard TOWGS84 moves a UK point by the published
    ~100m, matching the OS Net example to single-transformation accuracy."""
    t = Transform(WGS84_GEO, OSGB36_GEO)
    lon, lat = t.transform(np.array([1.716073973]), np.array([52.658007833]))
    assert abs(lon[0] - 1.7179229) < 5e-5   # ~+124m east
    assert abs(lat[0] - 52.6575687) < 5e-5  # ~-49m south
    # exact roundtrip (the method is sign-reversible)
    inv = Transform(OSGB36_GEO, WGS84_GEO)
    lon2, lat2 = inv.transform(lon, lat)
    assert abs(lon2[0] - 1.716073973) < 1e-7
    assert abs(lat2[0] - 52.658007833) < 1e-7


def test_no_towgs84_means_wgs84_equivalent():
    """Datums without a declared shift keep the old behavior: treated as
    WGS84-equivalent (modern datums are within ~1m)."""
    t = Transform("EPSG:4167", "EPSG:4326")  # NZGD2000 (no TOWGS84) -> WGS84
    lon, lat = t.transform(np.array([173.0]), np.array([-41.0]))
    assert lon[0] == 173.0 and lat[0] == -41.0


def test_mercator_1sp_honours_central_meridian():
    """EPSG:3832 (PDC Mercator, central_meridian 150): lon 180 maps 30deg
    east of the projection origin — the round-1 implementation ignored the
    central meridian, shifting the Pacific by 150 degrees."""
    wkt_3832 = (
        'PROJCS["WGS 84 / PDC Mercator",GEOGCS["WGS 84",DATUM["WGS_1984",'
        'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
        'UNIT["degree",0.0174532925199433]],PROJECTION["Mercator_1SP"],'
        'PARAMETER["central_meridian",150],PARAMETER["scale_factor",1],'
        'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
        'UNIT["metre",1],AUTHORITY["EPSG","3832"]]'
    )
    t = Transform("EPSG:4326", wkt_3832)
    x, y = t.transform(np.array([180.0]), np.array([0.0]))
    assert abs(x[0] - 6378137 * np.radians(30.0)) < 1.0
    assert abs(y[0]) < 1e-6
    inv = Transform(wkt_3832, "EPSG:4326")
    lon, lat = inv.transform(x, y)
    assert abs(lon[0] - 180.0) < 1e-9 and abs(lat[0]) < 1e-9


def test_mercator_ellipsoidal_vs_web_spherical():
    """Mercator_1SP on WGS84 is ellipsoidal; EPSG:3857 stays spherical
    despite its WKT claiming Mercator_1SP. At lat 45 they differ by ~30km
    in northing."""
    wkt_merc = (
        'PROJCS["World Mercator",GEOGCS["WGS 84",DATUM["WGS_1984",'
        'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
        'UNIT["degree",0.0174532925199433]],PROJECTION["Mercator_1SP"],'
        'PARAMETER["central_meridian",0],PARAMETER["scale_factor",1],'
        'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
        'UNIT["metre",1],AUTHORITY["EPSG","3395"]]'
    )
    t_ell = Transform("EPSG:4326", wkt_merc)
    t_sph = Transform("EPSG:4326", "EPSG:3857")
    _, y_ell = t_ell.transform(np.array([0.0]), np.array([45.0]))
    _, y_sph = t_sph.transform(np.array([0.0]), np.array([45.0]))
    # EPSG:3395 at lat 45: 5591295.92m (published); 3857: 5621521.49m
    assert abs(y_ell[0] - 5591295.92) < 1.0
    assert abs(y_sph[0] - 5621521.49) < 1.0


LCC_SPHERE = (
    'PROJCS["test LCC sphere",GEOGCS["sphere",DATUM["sphere",'
    'SPHEROID["sphere",6370997,0]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic_2SP"],'
    'PARAMETER["standard_parallel_1",33],PARAMETER["standard_parallel_2",45],'
    'PARAMETER["latitude_of_origin",23],PARAMETER["central_meridian",-96],'
    'PARAMETER["false_easting",0],PARAMETER["false_northing",0],UNIT["metre",1]]'
)
SPHERE_GEO = (
    'GEOGCS["sphere",DATUM["sphere",SPHEROID["sphere",6370997,0]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]]'
)


def test_lcc_spherical_ellipsoid_no_crash():
    """LCC on SPHEROID[...,0] (a sphere) raised ZeroDivisionError before the
    r2 advisor fix; it must behave like the e=0 degenerate case and
    round-trip cleanly."""
    t = Transform(SPHERE_GEO, LCC_SPHERE)
    x, y = t.transform(np.array([-75.0]), np.array([35.0]))
    assert np.all(np.isfinite(x)) and np.all(np.isfinite(y))
    inv = Transform(LCC_SPHERE, SPHERE_GEO)
    lon, lat = inv.transform(x, y)
    assert abs(lon[0] + 75.0) < 1e-7
    assert abs(lat[0] - 35.0) < 1e-7


class TestNewProjections:
    def test_albers_snyder_example(self):
        """Snyder 1987 numerical example for Albers (Clarke 1866, sp
        29.5/45.5, origin 23N 96W): (35N, 75W) -> 1885472.7, 1535925.0."""
        from kart_tpu.crs import CRS, Transform

        wkt = (
            'PROJCS["Albers test",GEOGCS["NAD27",DATUM["North_American_Datum_1927",'
            'SPHEROID["Clarke 1866",6378206.4,294.978698213898]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            'PROJECTION["Albers_Conic_Equal_Area"],'
            'PARAMETER["standard_parallel_1",29.5],'
            'PARAMETER["standard_parallel_2",45.5],'
            'PARAMETER["latitude_of_origin",23],'
            'PARAMETER["central_meridian",-96],'
            'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
            'UNIT["metre",1]]'
        )
        crs = CRS(wkt)
        from kart_tpu.crs import _albers_forward, _albers_inverse

        x, y = _albers_forward(crs, -75.0, 35.0)
        assert abs(float(x) - 1885472.7) < 1.0, float(x)
        assert abs(float(y) - 1535925.0) < 1.0, float(y)
        lon, lat = _albers_inverse(crs, x, y)
        assert abs(float(lon) - -75.0) < 1e-8
        assert abs(float(lat) - 35.0) < 1e-8

    def test_polar_stereographic_ups_north(self):
        """EPSG 9810 variant A example (UPS North): (73N, 44E) ->
        3320416.75, 632668.43 with k0=0.994, FE=FN=2000000."""
        from kart_tpu.crs import CRS, _polar_stereo_forward, _polar_stereo_inverse

        wkt = (
            'PROJCS["UPS North",GEOGCS["WGS 84",DATUM["WGS_1984",'
            'SPHEROID["WGS 84",6378137,298.257223563]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            'PROJECTION["Polar_Stereographic"],'
            'PARAMETER["latitude_of_origin",90],'
            'PARAMETER["central_meridian",0],'
            'PARAMETER["scale_factor",0.994],'
            'PARAMETER["false_easting",2000000],'
            'PARAMETER["false_northing",2000000],UNIT["metre",1]]'
        )
        crs = CRS(wkt)
        x, y = _polar_stereo_forward(crs, 44.0, 73.0)
        assert abs(float(x) - 3320416.75) < 0.5, float(x)
        assert abs(float(y) - 632668.43) < 0.5, float(y)
        lon, lat = _polar_stereo_inverse(crs, x, y)
        assert abs(float(lon) - 44.0) < 1e-8
        assert abs(float(lat) - 73.0) < 1e-8

    def test_polar_stereographic_south_roundtrip(self):
        """Variant B, south pole (Antarctic-style std parallel -71)."""
        import numpy as np

        from kart_tpu.crs import CRS, _polar_stereo_forward, _polar_stereo_inverse

        wkt = (
            'PROJCS["Antarctic",GEOGCS["WGS 84",DATUM["WGS_1984",'
            'SPHEROID["WGS 84",6378137,298.257223563]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            'PROJECTION["Polar_Stereographic"],'
            'PARAMETER["latitude_of_origin",-71],'
            'PARAMETER["central_meridian",70],'
            'PARAMETER["false_easting",6000000],'
            'PARAMETER["false_northing",6000000],UNIT["metre",1]]'
        )
        crs = CRS(wkt)
        lons = np.array([70.0, 120.0, -60.0, 0.0])
        lats = np.array([-71.0, -75.0, -80.0, -89.5])
        x, y = _polar_stereo_forward(crs, lons, lats)
        lon2, lat2 = _polar_stereo_inverse(crs, x, y)
        assert np.allclose(lon2, lons, atol=1e-8)
        assert np.allclose(lat2, lats, atol=1e-8)
        # the pole maps to the false origin
        xp, yp = _polar_stereo_forward(crs, 0.0, -90.0)
        assert abs(float(xp) - 6000000) < 1e-3
        assert abs(float(yp) - 6000000) < 1e-3

    def test_oblique_stereographic_rd_new(self):
        """EPSG 9809 example (Amersfoort / RD New): (53N, 6E) ->
        196105.283, 557057.739."""
        from kart_tpu.crs import (
            CRS,
            _oblique_stereo_forward,
            _oblique_stereo_inverse,
        )

        wkt = (
            'PROJCS["Amersfoort / RD New",GEOGCS["Amersfoort",'
            'DATUM["Amersfoort",SPHEROID["Bessel 1841",6377397.155,299.1528128]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            'PROJECTION["Oblique_Stereographic"],'
            'PARAMETER["latitude_of_origin",52.1561605555556],'
            'PARAMETER["central_meridian",5.38763888888889],'
            'PARAMETER["scale_factor",0.9999079],'
            'PARAMETER["false_easting",155000],'
            'PARAMETER["false_northing",463000],UNIT["metre",1]]'
        )
        crs = CRS(wkt)
        x, y = _oblique_stereo_forward(crs, 6.0, 53.0)
        assert abs(float(x) - 196105.283) < 0.05, float(x)
        assert abs(float(y) - 557057.739) < 0.05, float(y)
        lon, lat = _oblique_stereo_inverse(crs, x, y)
        assert abs(float(lon) - 6.0) < 1e-8
        assert abs(float(lat) - 53.0) < 1e-8

    def test_albers_roundtrip_grid_and_transform_api(self):
        import numpy as np

        from kart_tpu.crs import Transform, WGS84_WKT

        wkt = (
            'PROJCS["conus albers",GEOGCS["WGS 84",DATUM["WGS_1984",'
            'SPHEROID["WGS 84",6378137,298.257223563]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            'PROJECTION["Albers_Conic_Equal_Area"],'
            'PARAMETER["standard_parallel_1",29.5],'
            'PARAMETER["standard_parallel_2",45.5],'
            'PARAMETER["latitude_of_center",23],'
            'PARAMETER["longitude_of_center",-96],'
            'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
            'UNIT["metre",1]]'
        )
        t = Transform(WGS84_WKT, wkt)
        lons = np.array([-120.0, -96.0, -75.0, -66.0])
        lats = np.array([49.0, 23.0, 35.0, 18.0])
        x, y = t.transform(lons, lats)
        back = Transform(wkt, WGS84_WKT)
        lon2, lat2 = back.transform(x, y)
        assert np.allclose(lon2, lons, atol=1e-7)
        assert np.allclose(lat2, lats, atol=1e-7)


class TestNTv2GridShift:
    @staticmethod
    def _write_gsb(path, *, lat_shift_sec=1.8, lon_shift_sec=-2.4):
        """A minimal valid NTv2 file: one subgrid covering lat 40..42N,
        lon 74..76W (NTv2 longitudes positive west), 0.5-degree cells, with
        a linear lat-shift field and constant lon shift."""
        import struct

        import numpy as np

        def rec(name, value, kind):
            out = name.ljust(8).encode()
            if kind == "i":
                return out + struct.pack("<i", value) + b"\x00\x00\x00\x00"
            if kind == "d":
                return out + struct.pack("<d", value)
            return out + value.ljust(8).encode()[:8]

        s_lat, n_lat = 40 * 3600.0, 42 * 3600.0
        e_long, w_long = 74 * 3600.0, 76 * 3600.0
        inc = 0.5 * 3600.0
        n_rows = int((n_lat - s_lat) / inc) + 1
        n_cols = int((w_long - e_long) / inc) + 1
        header = b"".join(
            [
                rec("NUM_OREC", 11, "i"),
                rec("NUM_SREC", 11, "i"),
                rec("NUM_FILE", 1, "i"),
                rec("GS_TYPE", "SECONDS", "s"),
                rec("VERSION", "NTv2.0", "s"),
                rec("SYSTEM_F", "TESTDATM", "s"),
                rec("SYSTEM_T", "WGS84", "s"),
                rec("MAJOR_F", 6378137.0, "d"),
                rec("MINOR_F", 6356752.314, "d"),
                rec("MAJOR_T", 6378137.0, "d"),
                rec("MINOR_T", 6356752.314, "d"),
                rec("SUB_NAME", "TEST", "s"),
                rec("PARENT", "NONE", "s"),
                rec("CREATED", "20260101", "s"),
                rec("UPDATED", "20260101", "s"),
                rec("S_LAT", s_lat, "d"),
                rec("N_LAT", n_lat, "d"),
                rec("E_LONG", e_long, "d"),
                rec("W_LONG", w_long, "d"),
                rec("LAT_INC", inc, "d"),
                rec("LONG_INC", inc, "d"),
                rec("GS_COUNT", n_rows * n_cols, "i"),
            ]
        )
        nodes = []
        for r in range(n_rows):
            for c in range(n_cols):
                # lat shift varies linearly with row; lon shift constant
                nodes.append(
                    struct.pack(
                        "<4f", lat_shift_sec * r / (n_rows - 1), lon_shift_sec, 0, 0
                    )
                )
        with open(path, "wb") as f:
            f.write(header + b"".join(nodes))
        return n_rows, n_cols

    def test_parse_and_bilinear(self, tmp_path):
        import numpy as np

        from kart_tpu.gridshift import NTv2Grid

        gsb = tmp_path / "test.gsb"
        self._write_gsb(gsb)
        grid = NTv2Grid.open(str(gsb))
        assert grid.system_from == "TESTDATM"
        (sg,) = grid.subgrids
        assert (sg.n_rows, sg.n_cols) == (5, 5)

        # at the south edge the lat shift is 0; at the north edge 1.8"
        lon, lat = grid.shift(np.array([-75.0]), np.array([40.0]))
        assert abs(lat[0] - 40.0) < 1e-12
        lon, lat = grid.shift(np.array([-75.0]), np.array([42.0]))
        assert abs(lat[0] - (42.0 + 1.8 / 3600)) < 1e-9
        # halfway: half the shift (bilinear)
        lon, lat = grid.shift(np.array([-75.0]), np.array([41.0]))
        assert abs(lat[0] - (41.0 + 0.9 / 3600)) < 1e-9
        # lon shift -2.4" positive-west means +2.4" east-positive
        assert abs(lon[0] - (-75.0 + 2.4 / 3600)) < 1e-9
        # outside the grid: fail open, unchanged
        lon, lat = grid.shift(np.array([10.0]), np.array([0.0]))
        assert lon[0] == 10.0 and lat[0] == 0.0
        # inverse round-trips
        flon, flat = grid.shift(np.array([-75.3]), np.array([41.3]))
        blon, blat = grid.shift(flon, flat, inverse=True)
        assert abs(blon[0] - -75.3) < 1e-10 and abs(blat[0] - 41.3) < 1e-10

    def test_transform_uses_registered_grid(self, tmp_path):
        import numpy as np

        from kart_tpu import gridshift
        from kart_tpu.crs import Transform, WGS84_WKT
        from kart_tpu.gridshift import NTv2Grid

        gsb = tmp_path / "test.gsb"
        self._write_gsb(gsb)
        src_wkt = WGS84_WKT.replace("WGS_1984", "TESTDATM").replace(
            'GEOGCS["WGS 84"', 'GEOGCS["Test Datum"'
        )
        try:
            gridshift.clear_grids()
            gridshift.register_grid("TESTDATM", NTv2Grid.open(str(gsb)))
            t = Transform(src_wkt, WGS84_WKT)
            lon, lat = t.transform(np.array([-75.0]), np.array([42.0]))
            assert abs(lat[0] - (42.0 + 1.8 / 3600)) < 1e-9
        finally:
            gridshift.clear_grids()

    def test_env_dir_scan(self, tmp_path, monkeypatch):
        from kart_tpu import gridshift

        self._write_gsb(tmp_path / "a.gsb")
        monkeypatch.setenv("KART_NTV2_GRID_DIR", str(tmp_path))
        try:
            gridshift.clear_grids()
            assert gridshift.grid_for_datum("TESTDATM") is not None
            assert gridshift.grid_for_datum("testdatm") is not None  # normalised
            assert gridshift.grid_for_datum("other") is None
        finally:
            gridshift.clear_grids()


class TestDatumShiftComposition:
    def test_grid_composes_with_helmert_destination(self, tmp_path):
        """Grid src -> WGS84 must still apply the destination's TOWGS84
        Helmert: a zero-shift grid + a dx=100m dst Helmert moves the
        coordinate, not returns it unchanged."""
        import numpy as np

        from kart_tpu import gridshift
        from kart_tpu.crs import CRS, Transform, WGS84_WKT, _datum_shift
        from kart_tpu.gridshift import NTv2Grid

        gsb = tmp_path / "zero.gsb"
        TestNTv2GridShift._write_gsb(gsb, lat_shift_sec=0.0, lon_shift_sec=0.0)
        src_wkt = WGS84_WKT.replace("WGS_1984", "GRIDDATUM")
        dst_wkt = (
            'GEOGCS["shifted",DATUM["Shifted_Datum",'
            'SPHEROID["WGS 84",6378137,298.257223563],'
            'TOWGS84[100,0,0,0,0,0,0]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]]'
        )
        try:
            gridshift.clear_grids()
            gridshift.register_grid("GRIDDATUM", NTv2Grid.open(str(gsb)))
            lon, lat = _datum_shift(
                CRS(src_wkt), CRS(dst_wkt), np.array([-75.0]), np.array([41.0])
            )
            # dx=100m at lon -75: the longitude must move by roughly
            # 100*cos(lon)/(a*cos(lat)) rad — definitely not zero
            assert abs(lon[0] - -75.0) > 1e-5
        finally:
            gridshift.clear_grids()

    def test_same_grid_both_spellings_is_noop(self, tmp_path):
        import numpy as np

        from kart_tpu import gridshift
        from kart_tpu.crs import CRS, WGS84_WKT, _datum_shift
        from kart_tpu.gridshift import NTv2Grid

        gsb = tmp_path / "g.gsb"
        TestNTv2GridShift._write_gsb(gsb)
        a_wkt = WGS84_WKT.replace("WGS_1984", "NAD27")
        b_wkt = WGS84_WKT.replace("WGS_1984", "North_American_Datum_1927")
        try:
            gridshift.clear_grids()
            grid = NTv2Grid.open(str(gsb))
            gridshift.register_grid("NAD27", grid)
            gridshift.register_grid("North_American_Datum_1927", grid)
            lon, lat = _datum_shift(
                CRS(a_wkt), CRS(b_wkt), np.array([-75.0]), np.array([41.0])
            )
            assert lon[0] == -75.0 and lat[0] == 41.0
        finally:
            gridshift.clear_grids()

    def test_corrupt_gsb_in_env_dir_is_skipped(self, tmp_path, monkeypatch):
        from kart_tpu import gridshift

        (tmp_path / "bad.gsb").write_bytes(b"NUM_OREC" + b"\x0b\x00\x00\x00junk")
        TestNTv2GridShift._write_gsb(tmp_path / "good.gsb")
        monkeypatch.setenv("KART_NTV2_GRID_DIR", str(tmp_path))
        try:
            gridshift.clear_grids()
            assert gridshift.grid_for_datum("TESTDATM") is not None
        finally:
            gridshift.clear_grids()

    def test_minutes_grid_rejected(self, tmp_path):
        import struct

        import pytest

        from kart_tpu.gridshift import GridShiftError, NTv2Grid

        gsb = tmp_path / "m.gsb"
        TestNTv2GridShift._write_gsb(gsb)
        data = bytearray(gsb.read_bytes())
        data[3 * 16 + 8 : 3 * 16 + 16] = b"MINUTES "
        gsb.write_bytes(bytes(data))
        with pytest.raises(GridShiftError, match="SECONDS"):
            NTv2Grid.open(str(gsb))


class TestNTv2SubgridOrder:
    def test_child_listed_before_parent_still_wins(self):
        """The .gsb format doesn't guarantee parents precede children
        (ADVICE r3): hierarchy order comes from the PARENT field, so a
        child listed first must still overwrite its parent's coarse value."""
        import numpy as np

        from kart_tpu.gridshift import NTv2Grid, SubGrid

        def make_sg(name, parent, s_lat, n_lat, e_long, w_long, shift_sec):
            sg = SubGrid()
            sg.name = name
            sg.parent = parent
            sg.s_lat, sg.n_lat = s_lat * 3600.0, n_lat * 3600.0
            sg.e_long, sg.w_long = e_long * 3600.0, w_long * 3600.0
            sg.lat_inc = sg.lon_inc = 0.5 * 3600.0
            sg.n_rows = int((sg.n_lat - sg.s_lat) / sg.lat_inc) + 1
            sg.n_cols = int((sg.w_long - sg.e_long) / sg.lon_inc) + 1
            sg.lat_shift = np.full((sg.n_rows, sg.n_cols), shift_sec)
            sg.lon_shift = np.zeros((sg.n_rows, sg.n_cols))
            return sg

        child = make_sg("FINE", "COARSE", 40.5, 41.0, 74.5, 75.0, 3.6)
        parent = make_sg("COARSE", "NONE", 40.0, 42.0, 74.0, 76.0, 1.8)
        # child FIRST in file order — the constructor must reorder
        grid = NTv2Grid("A", "B", [child, parent])
        assert [sg.name for sg in grid.subgrids] == ["COARSE", "FINE"]
        lon, lat = grid.shift(np.array([-74.75]), np.array([40.75]))
        assert abs(lat[0] - (40.75 + 3.6 / 3600)) < 1e-9  # fine value
        lon, lat = grid.shift(np.array([-75.5]), np.array([41.5]))
        assert abs(lat[0] - (41.5 + 1.8 / 3600)) < 1e-9  # coarse elsewhere

    def test_parent_cycle_treated_as_roots(self):
        import numpy as np

        from kart_tpu.gridshift import NTv2Grid, SubGrid

        a = SubGrid()
        a.name, a.parent = "A", "B"
        b = SubGrid()
        b.name, b.parent = "B", "A"
        for sg in (a, b):
            sg.s_lat, sg.n_lat = 0.0, 3600.0
            sg.e_long, sg.w_long = 0.0, 3600.0
            sg.lat_inc = sg.lon_inc = 3600.0
            sg.n_rows = sg.n_cols = 2
            sg.lat_shift = np.zeros((2, 2))
            sg.lon_shift = np.zeros((2, 2))
        grid = NTv2Grid("A", "B", [a, b])  # must not recurse forever
        assert len(grid.subgrids) == 2


class TestEpsgRegistry:
    """Built-in EPSG parameter table (VERDICT r3 missing #2): bare codes
    resolve without PROJ, transforms hit the projection origins exactly,
    unknown codes fail with a coverage listing."""

    def test_projected_origins_exact(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        # (code, geographic origin lon/lat, expected easting/northing)
        cases = [
            (27700, (-2.0, 49.0), (400000.0, -100000.0)),  # OSGB natural origin
            (2154, (3.0, 46.5), (700000.0, 6600000.0)),  # Lambert-93
            (3577, (132.0, 0.0), (0.0, 0.0)),  # Australian Albers
            (5070, (-96.0, 23.0), (0.0, 0.0)),  # CONUS Albers
            (28992, (5.38763888888889, 52.15616055555555), (155000.0, 463000.0)),
            (32661, (0.0, 90.0), (2000000.0, 2000000.0)),  # UPS North pole
            (26918, (-75.0, 0.0), (500000.0, 0.0)),  # NAD83 UTM 18N equator
            (25832, (9.0, 0.0), (500000.0, 0.0)),  # ETRS89 UTM 32N
            (28355, (147.0, 0.0), (500000.0, 10000000.0)),  # GDA94 MGA 55
            (7855, (147.0, 0.0), (500000.0, 10000000.0)),  # GDA2020 MGA 55
        ]
        for code, (lon, lat), (e, n) in cases:
            crs = make_crs(f"EPSG:{code}")
            assert crs.is_projected, code
            # project within the source CRS only (no datum shift): the
            # origin identity is a property of the projection itself
            fwd, _ = _PROJ_IMPLS[(crs.projection or "").lower()]
            x, y = fwd(crs, np.array([lon]), np.array([lat]))
            assert abs(x[0] - e) < 1e-3, (code, x[0], e)
            assert abs(y[0] - n) < 1e-3, (code, y[0], n)

    def test_projected_roundtrip(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        domains = {
            27700: (-5, 1.5, 50, 58),
            2154: (-4, 8, 42, 51),
            31370: (2.6, 6.3, 49.6, 51.4),
            28992: (3.5, 7, 50.8, 53.4),
            3577: (115, 150, -42, -12),
            3112: (115, 150, -42, -12),
            5070: (-120, -75, 25, 48),
            3005: (-138, -115, 48.5, 59),
            3347: (-120, -65, 43, 75),
            3031: (-180, 180, -85, -65),
            3413: (-120, 30, 62, 88),
            2180: (14.2, 24, 49.1, 54.8),
            26712: (-111, -105, 30, 48),
            23031: (0, 6, 38, 50),
        }
        rng = np.random.default_rng(5)
        for code, (w, e, s, n) in domains.items():
            crs = make_crs(f"EPSG:{code}")
            lon = rng.uniform(w, e, 50)
            lat = rng.uniform(s, n, 50)
            fwd, inv = _PROJ_IMPLS[(crs.projection or "").lower()]
            x, y = fwd(crs, lon, lat)
            lon2, lat2 = inv(crs, x, y)
            np.testing.assert_allclose(lon2, lon, atol=1e-8, err_msg=str(code))
            np.testing.assert_allclose(lat2, lat, atol=1e-8, err_msg=str(code))

    def test_datum_shift_applied_from_registry(self):
        import numpy as np

        from kart_tpu.crs import Transform

        # OSGB36 from the registry carries the 7-param TOWGS84: transforming
        # a point must move it by roughly the ~100m datum offset
        t = Transform("EPSG:4277", "EPSG:4326")
        lon, lat = t.transform(np.array([-2.0]), np.array([52.0]))
        assert 0.0005 < abs(lon[0] + 2.0) < 0.01  # ~50-600m shift in lon
        assert 0.0001 < abs(lat[0] - 52.0) < 0.01

    def test_geographic_codes_resolve(self):
        from kart_tpu.crs import make_crs

        for code in (4269, 4258, 4283, 7844, 4612, 6668, 4490, 4674, 4230):
            crs = make_crs(f"EPSG:{code}")
            assert crs.is_geographic, code
            assert str(crs.code) == str(code)

    def test_unknown_code_lists_coverage(self):
        import pytest

        from kart_tpu.crs import CrsError, make_crs

        with pytest.raises(CrsError) as ei:
            make_crs("EPSG:27200")  # NZGD49 / NZ Map Grid: method unsupported
        msg = str(ei.value)
        assert "EPSG:27200" in msg
        assert "UTM" in msg  # coverage listing present
        assert "full WKT" in msg


class TestLambertAzimuthalEqualArea:
    """EPSG method 9820 (ETRS89-LAEA Europe is EPSG:3035, the EU standard
    grid). Validated against the EPSG Guidance Note 7-2 worked example."""

    def test_epsg_worked_example(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:3035")
        fwd, inv = _PROJ_IMPLS["lambert_azimuthal_equal_area"]
        # GN7-2 §3.2.2: 50N 5E -> E 3962799.45, N 2999718.85
        x, y = fwd(crs, np.array([5.0]), np.array([50.0]))
        assert abs(x[0] - 3962799.45) < 0.01
        assert abs(y[0] - 2999718.85) < 0.01
        # natural origin maps exactly to the false origin
        x0, y0 = fwd(crs, np.array([10.0]), np.array([52.0]))
        assert abs(x0[0] - 4321000.0) < 1e-6
        assert abs(y0[0] - 3210000.0) < 1e-6

    def test_roundtrip(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:3035")
        fwd, inv = _PROJ_IMPLS["lambert_azimuthal_equal_area"]
        rng = np.random.default_rng(1)
        lon = rng.uniform(-10, 35, 500)
        lat = rng.uniform(34, 71, 500)
        x, y = fwd(crs, lon, lat)
        lon2, lat2 = inv(crs, x, y)
        np.testing.assert_allclose(lon2, lon, atol=1e-8)
        np.testing.assert_allclose(lat2, lat, atol=1e-7)

    def test_transform_through_registry(self):
        import numpy as np

        from kart_tpu.crs import Transform

        t = Transform("EPSG:4258", "EPSG:3035")
        x, y = t.transform(np.array([5.0]), np.array([50.0]))
        assert abs(x[0] - 3962799.45) < 0.01

    def test_polar_aspect_refused(self):
        import pytest

        from kart_tpu.crs import CrsError, Transform, make_crs

        wkt = (
            'PROJCS["polar laea",GEOGCS["WGS 84",DATUM["WGS_1984",'
            'SPHEROID["WGS 84",6378137,298.257223563]],'
            'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
            'PROJECTION["Lambert_Azimuthal_Equal_Area"],'
            'PARAMETER["latitude_of_center",90],'
            'PARAMETER["longitude_of_center",0],'
            'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
            'UNIT["metre",1]]'
        )
        t = Transform("EPSG:4326", wkt)
        with pytest.raises(CrsError, match="Polar-aspect"):
            t.transform([0.0], [80.0])


class TestCylindricalEqualArea:
    """EPSG method 9835; EPSG:6933 is NSIDC EASE-Grid 2.0 Global."""

    def test_roundtrip_and_known_extent(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:6933")
        fwd, inv = _PROJ_IMPLS["lambert_cylindrical_equal_area"]
        rng = np.random.default_rng(2)
        lon = rng.uniform(-179, 179, 500)
        lat = rng.uniform(-84, 84, 500)
        x, y = fwd(crs, lon, lat)
        lon2, lat2 = inv(crs, x, y)
        np.testing.assert_allclose(lon2, lon, atol=1e-8)
        np.testing.assert_allclose(lat2, lat, atol=1e-7)
        # published EASE-Grid 2.0 global extent: x = +/-17367530.45 m at
        # +/-180 lon (NSIDC grid definition)
        x180, _ = fwd(crs, np.array([180.0]), np.array([0.0]))
        assert abs(x180[0] - 17367530.45) < 1.0


def _numeric_area_scale(fwd, crs, lon, lat):
    """|det d(x,y)/d(lon,lat)| / (M N cos(lat)) — 1.0 for an equal-area
    projection (M, N: meridional / prime-vertical curvature radii)."""
    import math

    import numpy as np

    from kart_tpu.crs import _e2_of

    h = 1e-6
    x0, y0 = fwd(crs, lon, lat)
    x1, y1 = fwd(crs, lon + h, lat)
    x2, y2 = fwd(crs, lon, lat + h)
    dxdl = (x1 - x0) / math.radians(h)
    dydl = (y1 - y0) / math.radians(h)
    dxdp = (x2 - x0) / math.radians(h)
    dydp = (y2 - y0) / math.radians(h)
    det = np.abs(dxdl * dydp - dydl * dxdp)
    a = crs.semi_major
    e2 = _e2_of(crs)
    s = np.sin(np.radians(lat))
    m = a * (1 - e2) / (1 - e2 * s**2) ** 1.5
    n = a / np.sqrt(1 - e2 * s**2)
    return det / (m * n * np.cos(np.radians(lat)))


class TestEqualAreaProperty:
    """Independent validation: every equal-area projection's numeric
    Jacobian must equal the ellipsoidal area element everywhere."""

    def test_jacobians(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        cases = [
            ("EPSG:6933", "lambert_cylindrical_equal_area", (-170, 170, -80, 80)),
            ("EPSG:3035", "lambert_azimuthal_equal_area", (-8, 30, 36, 68)),
            ("EPSG:3577", "albers_conic_equal_area", (115, 150, -40, -12)),
        ]
        rng = np.random.default_rng(3)
        for code, method, (w, e, s, n) in cases:
            crs = make_crs(code)
            fwd, _ = _PROJ_IMPLS[method]
            lon = rng.uniform(w, e, 100)
            lat = rng.uniform(s, n, 100)
            scale = _numeric_area_scale(fwd, crs, lon, lat)
            np.testing.assert_allclose(
                scale, 1.0, rtol=2e-4, err_msg=f"{code} is not equal-area"
            )


class TestSwissObliqueMercator:
    """EPSG method 9814 / PROJ somerc (CH1903 LV03, CH1903+ LV95).
    Validated by construction properties: the projection must be CONFORMAL
    (meridian scale == parallel scale, directions orthogonal) everywhere,
    have unit scale at the projection centre (k0=1), map Bern's origin to
    the false origin exactly, and roundtrip to machine precision. Coarse
    Swiss city anchors guard against gross constant errors."""

    def _scales(self, fwd, crs, lon, lat):
        import math

        import numpy as np

        from kart_tpu.crs import _e2_of

        h = 1e-6
        x0, y0 = fwd(crs, lon, lat)
        x1, y1 = fwd(crs, lon + h, lat)
        x2, y2 = fwd(crs, lon, lat + h)
        dl = math.radians(h)
        a = crs.semi_major
        e2 = _e2_of(crs)
        s = np.sin(np.radians(lat))
        m = a * (1 - e2) / (1 - e2 * s**2) ** 1.5
        n = a / np.sqrt(1 - e2 * s**2)
        par = np.hypot(x1 - x0, y1 - y0) / (dl * n * np.cos(np.radians(lat)))
        mer = np.hypot(x2 - x0, y2 - y0) / (dl * m)
        dot = (x1 - x0) * (x2 - x0) + (y1 - y0) * (y2 - y0)
        cosang = dot / (np.hypot(x1 - x0, y1 - y0) * np.hypot(x2 - x0, y2 - y0))
        return par, mer, cosang

    def test_conformal_and_unit_scale_at_origin(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:2056")
        fwd, _ = _PROJ_IMPLS["hotine_oblique_mercator_azimuth_center"]
        rng = np.random.default_rng(5)
        lon = rng.uniform(5.9, 10.5, 200)
        lat = rng.uniform(45.8, 47.9, 200)
        par, mer, cosang = self._scales(fwd, crs, lon, lat)
        np.testing.assert_allclose(par, mer, rtol=1e-6)  # conformal
        np.testing.assert_allclose(cosang, 0.0, atol=1e-5)  # orthogonal
        # k0 = 1 at the projection centre
        par0, mer0, _ = self._scales(
            fwd, crs, np.array([7.439583333333333]), np.array([46.952405555555565])
        )
        np.testing.assert_allclose(par0, 1.0, rtol=1e-6)
        np.testing.assert_allclose(mer0, 1.0, rtol=1e-6)

    def test_origin_anchors_roundtrip(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        for code, e0, n0 in ((2056, 2600000, 1200000), (21781, 600000, 200000)):
            crs = make_crs(f"EPSG:{code}")
            fwd, inv = _PROJ_IMPLS["hotine_oblique_mercator_azimuth_center"]
            x, y = fwd(
                crs, np.array([7.439583333333333]), np.array([46.952405555555565])
            )
            assert abs(x[0] - e0) < 1e-6 and abs(y[0] - n0) < 1e-6
            rng = np.random.default_rng(6)
            lon = rng.uniform(5.9, 10.5, 300)
            lat = rng.uniform(45.8, 47.9, 300)
            X, Y = fwd(crs, lon, lat)
            lon2, lat2 = inv(crs, X, Y)
            np.testing.assert_allclose(lon2, lon, atol=1e-10)
            np.testing.assert_allclose(lat2, lat, atol=1e-10)
        # coarse anchors: Swiss cities land within ~2km of their LV95 spots
        crs = make_crs("EPSG:2056")
        fwd, _ = _PROJ_IMPLS["hotine_oblique_mercator_azimuth_center"]
        for lon, lat, ee, nn in (
            (6.14, 46.20, 2500000, 1118000),
            (8.54, 47.38, 2683000, 1247000),
        ):
            x, y = fwd(crs, np.array([lon]), np.array([lat]))
            assert np.hypot(x[0] - ee, y[0] - nn) < 2500

class TestHotineObliqueMercator:
    """General-azimuth Hotine Oblique Mercator, variants A (EPSG 9812) and
    B (9815) — previously only the Swiss azimuth=90 special case existed
    (VERDICT r4 next #8)."""

    def test_epsg_worked_example_variant_b(self):
        # EPSG Guidance Note 7-2: Timbalai 1948 / RSO Borneo (m)
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:29873")
        fwd, inv = _PROJ_IMPLS["hotine_oblique_mercator_azimuth_center"]
        lon = np.array([115 + 48 / 60 + 19.8196 / 3600])
        lat = np.array([5 + 23 / 60 + 14.1129 / 3600])
        e, n = fwd(crs, lon, lat)
        assert abs(e[0] - 679245.73) < 0.02
        assert abs(n[0] - 596562.78) < 0.02
        lon2, lat2 = inv(crs, e, n)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_variant_a_roundtrip_and_anchor(self):
        # GDM2000 / Peninsula RSO: KL lands near its published grid spot
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:3375")
        fwd, inv = _PROJ_IMPLS["hotine_oblique_mercator"]
        x, y = fwd(crs, np.array([101.69]), np.array([3.14]))
        # Kuala Lumpur ~ (412k, 347k) in Peninsula RSO
        assert np.hypot(x[0] - 412000, y[0] - 347000) < 5000
        rng = np.random.default_rng(7)
        lon = rng.uniform(100.0, 104.5, 300)
        lat = rng.uniform(1.2, 6.7, 300)
        X, Y = fwd(crs, lon, lat)
        lon2, lat2 = inv(crs, X, Y)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_swiss_special_case_still_exact(self):
        # azimuth=90 routes to the proven swisstopo double projection
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:21781")
        fwd, _ = _PROJ_IMPLS["hotine_oblique_mercator_azimuth_center"]
        x, y = fwd(
            crs, np.array([7.439583333333333]), np.array([46.952405555555565])
        )
        assert abs(x[0] - 600000) < 1e-6 and abs(y[0] - 200000) < 1e-6


class TestKrovak:
    """Krovak oblique conformal conic (EPSG method 9819) — S-JTSK 5514."""

    def test_epsg_worked_example(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:5514")
        fwd, inv = _PROJ_IMPLS["krovak"]
        lon = np.array([16 + 50 / 60 + 59.1790 / 3600])
        lat = np.array([50 + 12 / 60 + 32.4416 / 3600])
        e, n = fwd(crs, lon, lat)
        # GN7-2 gives southing X=1050538.63, westing Y=568991.00;
        # 5514 axes are east = -westing, north = -southing
        assert abs(e[0] - -568991.00) < 0.05
        assert abs(n[0] - -1050538.63) < 0.05
        lon2, lat2 = inv(crs, e, n)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_ferro_referenced_longitude(self):
        # EPSG 2065-style WKT carries 42°30' east of Ferro; same grid
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs
        from kart_tpu.epsg import epsg_wkt

        wkt = epsg_wkt(5514).replace(
            "24.833333333333332", "42.5"
        )
        crs = make_crs(wkt)
        fwd, _ = _PROJ_IMPLS["krovak"]
        e, n = fwd(crs, np.array([14.42]), np.array([50.088]))
        crs0 = make_crs("EPSG:5514")
        e0, n0 = fwd(crs0, np.array([14.42]), np.array([50.088]))
        np.testing.assert_allclose(e, e0, atol=1e-6)
        np.testing.assert_allclose(n, n0, atol=1e-6)

    def test_prague_anchor(self):
        import numpy as np

        from kart_tpu.crs import _PROJ_IMPLS, make_crs

        crs = make_crs("EPSG:5514")
        fwd, inv = _PROJ_IMPLS["krovak"]
        e, n = fwd(crs, np.array([14.42]), np.array([50.088]))
        # Prague ~ (-743km, -1043km) in Krovak East North
        assert np.hypot(e[0] - -743000, n[0] - -1043000) < 3000
        rng = np.random.default_rng(8)
        lon = rng.uniform(12.1, 22.5, 300)
        lat = rng.uniform(47.7, 51.1, 300)
        X, Y = fwd(crs, lon, lat)
        lon2, lat2 = inv(crs, X, Y)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)


class TestRegistryConsistency:
    """The epsg.py contract docstring promises every registered projected
    CRS resolves AND transforms through the engine — greps rot away, this
    executes the claim (VERDICT r4 weak #6)."""

    def test_every_projected_code_transforms(self):
        import numpy as np

        from kart_tpu.crs import Transform, make_crs
        from kart_tpu.epsg import PROJECTED

        # representative in-extent probe points per projection family
        probes = {
            5514: (15.0, 49.8), 29873: (115.2, 4.8), 3375: (102.0, 4.0),
            2056: (8.2, 46.8), 21781: (8.2, 46.8), 6933: (10.0, 45.0),
            3035: (10.0, 52.0),
        }
        for code in PROJECTED:
            crs = make_crs(f"EPSG:{code}")
            assert crs is not None, code
            lon, lat = probes.get(code, (crs.params.get(
                "central_meridian", crs.params.get("longitude_of_center", 0.0)
            ), 45.0))
            t = Transform("EPSG:4326", f"EPSG:{code}")
            x, y = t.transform(np.array([lon]), np.array([lat]))
            assert np.isfinite(x).all() and np.isfinite(y).all(), code
            t2 = Transform(f"EPSG:{code}", "EPSG:4326")
            lon2, lat2 = t2.transform(x, y)
            assert abs(lon2[0] - lon) < 1e-5 and abs(lat2[0] - lat) < 1e-5, code
