"""Mesh-sharded diff: identical counts to the single-chip and numpy paths.

Runs on whatever devices are live; the multi-device cases skip below 8
devices (use the virtual CPU mesh per tests/conftest.py).
"""

import numpy as np
import pytest

import jax

from kart_tpu.ops.blocks import FeatureBlock, pack_oid_hex
from kart_tpu.ops.diff_kernel import classify_blocks
from kart_tpu.parallel import make_mesh, partition_block, sharded_classify
from kart_tpu.parallel.sharded_diff import synthetic_block


def _blocks_with_edits(n=1000, n_ins=7, n_upd=11, n_del=5, seed=42):
    rng = np.random.default_rng(seed)
    keys = np.sort(rng.choice(10 * n, size=n, replace=False)).astype(np.int64)
    oids = rng.integers(0, 2**32, size=(n, 5), dtype=np.uint32)
    paths = [f"f/{k}" for k in keys]
    old = FeatureBlock.from_arrays(keys.copy(), oids.copy(), list(paths))

    new_keys = keys.copy()
    new_oids = oids.copy()
    del_idx = rng.choice(n, size=n_del, replace=False)
    keep = np.setdiff1d(np.arange(n), del_idx)
    new_keys = new_keys[keep]
    new_oids = new_oids[keep]
    upd_idx = rng.choice(len(new_keys), size=n_upd, replace=False)
    new_oids[upd_idx] = rng.integers(0, 2**32, size=(n_upd, 5), dtype=np.uint32)
    ins_keys = np.asarray(
        sorted(set(range(10 * n, 10 * n + n_ins))), dtype=np.int64
    )
    ins_oids = rng.integers(0, 2**32, size=(n_ins, 5), dtype=np.uint32)
    new_keys = np.concatenate([new_keys, ins_keys])
    new_oids = np.concatenate([new_oids, ins_oids])
    new_paths = [f"f/{k}" for k in new_keys]
    new = FeatureBlock.from_arrays(new_keys, new_oids, new_paths)
    return old, new, {"inserts": n_ins, "updates": n_upd, "deletes": n_del}


def test_partition_block_roundtrip():
    old, _, _ = _blocks_with_edits()
    keys, oids, counts, src = partition_block(old, 4)
    assert counts.sum() == old.count
    # every shard holds only keys with its own modulus, still sorted
    for s in range(4):
        real = keys[s, : counts[s]]
        assert np.all(real % 4 == s)
        assert np.all(np.diff(real) > 0)
        # src maps each slot back to the block row holding the same key
        rows = src[s, : counts[s]]
        assert np.array_equal(old.keys[rows], real)
        assert np.all(src[s, counts[s] :] == -1)
    # every block row appears exactly once across shards
    all_rows = src[src >= 0]
    assert np.array_equal(np.sort(all_rows), np.arange(old.count))


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_sharded_counts_match_single_chip(n_shards):
    if jax.device_count() < n_shards:
        pytest.skip(f"needs {n_shards} devices")
    old, new, expected = _blocks_with_edits()
    _, _, single_counts = classify_blocks(old, new)
    mesh = make_mesh(n_shards)
    _, _, sharded_counts, _ = sharded_classify(mesh, old, new)
    assert single_counts == expected
    assert sharded_counts == expected


def test_sharded_classify_classes_cover_all_changes():
    n_shards = min(jax.device_count(), 8)
    old, new, expected = _blocks_with_edits(n=4096, n_ins=13, n_upd=29, n_del=17)
    mesh = make_mesh(n_shards)
    old_class, new_class, counts, (old_part, new_part) = sharded_classify(
        mesh, old, new
    )
    assert counts == expected
    from kart_tpu.ops.diff_kernel import DELETE, INSERT, UPDATE

    assert int((new_class == INSERT).sum()) == expected["inserts"]
    assert int((old_class == UPDATE).sum()) == expected["updates"]
    assert int((old_class == DELETE).sum()) == expected["deletes"]
    # classes only ever set on real rows
    for s in range(n_shards):
        assert np.all(old_class[s, old_part[2][s] :] == 0)
        assert np.all(new_class[s, new_part[2][s] :] == 0)


def test_classify_blocks_sharded_matches_single_chip():
    """The production mesh entry point returns block-row-order classes
    bit-identical to the single-chip classify."""
    from kart_tpu.parallel.sharded_diff import STATS, classify_blocks_sharded

    old, new, expected = _blocks_with_edits(n=2048, n_ins=19, n_upd=23, n_del=31)
    single_old, single_new, single_counts = classify_blocks(old, new)
    before = STATS["sharded_classify_calls"]
    sh_old, sh_new, sh_counts = classify_blocks_sharded(old, new)
    assert STATS["sharded_classify_calls"] == before + 1
    assert sh_counts == single_counts == expected
    assert np.array_equal(sh_old, single_old)
    assert np.array_equal(sh_new, single_new)


def test_should_shard_env_override(monkeypatch):
    from kart_tpu.parallel.sharded_diff import should_shard

    monkeypatch.setenv("KART_DIFF_SHARDED", "0")
    assert not should_shard(10**9)
    monkeypatch.setenv("KART_DIFF_SHARDED", "1")
    if jax.device_count() >= 2:
        assert should_shard(10)
    monkeypatch.setenv("KART_DIFF_SHARDED", "auto")
    assert not should_shard(10)  # far below the crossover


def test_engine_routes_through_mesh(tmp_path, monkeypatch):
    """A real CLI diff (repo + sidecars) runs the mesh path when forced —
    the VERDICT r2 gap: sharding must be reachable from `kart diff`, not
    only from synthetic blocks."""
    import json

    from helpers import make_repo_with_edits

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    from kart_tpu.parallel.sharded_diff import STATS

    repo_path, expected = make_repo_with_edits(tmp_path)
    monkeypatch.setenv("KART_DIFF_SHARDED", "1")
    monkeypatch.setenv("KART_DIFF_ENGINE", "columnar")
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    before = STATS["sharded_classify_calls"]
    result = CliRunner().invoke(
        cli,
        ["-C", repo_path, "diff", "HEAD^...HEAD", "-o", "json"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert STATS["sharded_classify_calls"] > before
    diff = json.loads(result.output)["kart.diff/v1+hexwkb"]
    ds = diff[next(iter(diff))]
    assert len(ds["feature"]) == sum(expected.values())


def test_synthetic_block_deterministic():
    a = synthetic_block(100, seed=1)
    b = synthetic_block(100, seed=1)
    assert np.array_equal(a.oids, b.oids)
    assert a.count == 100


def _merge_blocks(n=3000, seed=9):
    """(ancestor, ours, theirs) with a known mix of edits/conflicts."""
    from kart_tpu.parallel.sharded_diff import synthetic_block

    anc = synthetic_block(n, seed=seed)
    ours = synthetic_block(n, seed=seed)
    ours.oids = ours.oids.copy()
    theirs = synthetic_block(n, seed=seed)
    theirs.oids = theirs.oids.copy()
    rng = np.random.default_rng(seed + 1)
    both = rng.choice(n, size=n // 10, replace=False)  # conflicts
    ours_only = rng.choice(n, size=n // 7, replace=False)
    theirs_only = rng.choice(n, size=n // 5, replace=False)
    ours.oids[both, 0] ^= 1
    theirs.oids[both, 0] ^= 2
    ours.oids[ours_only, 1] ^= 3
    theirs.oids[theirs_only, 2] ^= 4
    return anc, ours, theirs


def test_sharded_merge_matches_single_chip(monkeypatch):
    """sharded_merge_classify must reproduce merge_classify exactly: same
    global union order, decisions, presence bits, stats."""
    from kart_tpu.ops.merge_kernel import merge_classify
    from kart_tpu.parallel.sharded_diff import STATS
    from kart_tpu.parallel.sharded_merge import sharded_merge_classify

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    anc, ours, theirs = _merge_blocks()
    monkeypatch.setenv("KART_DIFF_SHARDED", "0")  # single-chip baseline
    union_s, dec_s, pres_s, stats_s = merge_classify(anc, ours, theirs)
    before = STATS["sharded_merge_calls"]
    union_m, dec_m, pres_m, stats_m = sharded_merge_classify(anc, ours, theirs)
    assert STATS["sharded_merge_calls"] == before + 1
    np.testing.assert_array_equal(union_m, union_s)
    np.testing.assert_array_equal(dec_m, dec_s)
    np.testing.assert_array_equal(pres_m, pres_s)
    assert stats_m == stats_s
    assert stats_m["conflicts"] > 0


def test_merge_classify_routes_through_mesh(monkeypatch):
    """KART_DIFF_SHARDED=1 routes merge_classify itself onto the mesh."""
    from kart_tpu.ops.merge_kernel import merge_classify
    from kart_tpu.parallel.sharded_diff import STATS

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    anc, ours, theirs = _merge_blocks(n=1500, seed=4)
    monkeypatch.setenv("KART_DIFF_SHARDED", "0")
    expected = merge_classify(anc, ours, theirs)
    monkeypatch.setenv("KART_DIFF_SHARDED", "1")
    before = STATS["sharded_merge_calls"]
    got = merge_classify(anc, ours, theirs)
    assert STATS["sharded_merge_calls"] == before + 1
    for a, b in zip(got[:3], expected[:3]):
        np.testing.assert_array_equal(a, b)
    assert got[3] == expected[3]


def test_estimation_routes_through_mesh(monkeypatch):
    """Device-sharded estimation rides the mesh when forced, matching the
    single-chip estimate."""
    from kart_tpu.diff.estimation import estimate_counts_from_blocks
    from kart_tpu.parallel.sharded_diff import STATS, synthetic_block

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    old, new, expected = _blocks_with_edits(n=4096, n_ins=11, n_upd=37, n_del=13)
    monkeypatch.setenv("KART_DIFF_SHARDED", "0")
    single = estimate_counts_from_blocks(old, new, "good")
    monkeypatch.setenv("KART_DIFF_SHARDED", "1")
    before = STATS["sharded_classify_calls"]
    sharded = estimate_counts_from_blocks(old, new, "good")
    assert STATS["sharded_classify_calls"] > before
    assert sharded == single
