import json
import os
import sqlite3

import pytest
from click.testing import CliRunner

from kart_tpu.cli import cli
from helpers import create_points_gpkg


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def repo_dir(tmp_path, runner, monkeypatch):
    """An initialised repo with an imported points layer + working copy."""
    gpkg = create_points_gpkg(str(tmp_path / "source.gpkg"), n=10)
    repo_dir = tmp_path / "repo"
    r = runner.invoke(cli, ["init", str(repo_dir), "--workingcopy-location", "wc.gpkg"])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(repo_dir)
    os.environ.setdefault("GIT_AUTHOR_NAME", "Tester")
    from kart_tpu.core.repo import KartRepo

    KartRepo(str(repo_dir)).config.set_many(
        {"user.name": "Tester", "user.email": "t@example.com"}
    )
    r = runner.invoke(cli, ["import", str(gpkg)])
    assert r.exit_code == 0, r.output
    return repo_dir


def wc_edit(repo_dir, sql):
    from helpers import wc_connect

    con = wc_connect(repo_dir / "wc.gpkg")
    con.executescript(sql)
    con.commit()
    con.close()


def test_init_empty(tmp_path, runner):
    r = runner.invoke(cli, ["init", str(tmp_path / "empty")])
    assert r.exit_code == 0
    assert "Initialized empty Kart repository" in r.output


def test_data_ls(repo_dir, runner):
    r = runner.invoke(cli, ["data", "ls"])
    assert r.exit_code == 0
    assert r.output.strip() == "points"
    r = runner.invoke(cli, ["data", "ls", "-o", "json"])
    assert json.loads(r.output)["kart.data.ls/v1"] == ["points"]


def test_data_version(repo_dir, runner):
    r = runner.invoke(cli, ["data", "version", "-o", "json"])
    assert json.loads(r.output)["repostructure.version"] == 3


def test_meta_get(repo_dir, runner):
    r = runner.invoke(cli, ["meta", "get", "points", "-o", "json"])
    assert r.exit_code == 0, r.output
    items = json.loads(r.output)["points"]
    assert items["title"] == "points title"
    assert any(c["name"] == "fid" for c in items["schema.json"])
    assert "crs/EPSG:4326.wkt" in items


def test_status_clean(repo_dir, runner):
    r = runner.invoke(cli, ["status"])
    assert "Nothing to commit, working copy clean" in r.output
    r = runner.invoke(cli, ["status", "-o", "json"])
    payload = json.loads(r.output)["kart.status/v1"]
    assert payload["branch"] == "main"
    assert payload["workingCopy"]["changes"] is None


def test_wc_edit_status_diff_commit(repo_dir, runner):
    wc_edit(
        repo_dir,
        "UPDATE points SET rating = 9.5 WHERE fid = 1;"
        "DELETE FROM points WHERE fid = 2;"
        "INSERT INTO points (fid, name) VALUES (50, 'added');",
    )
    r = runner.invoke(cli, ["status"])
    assert "1 inserts" in r.output and "1 updates" in r.output and "1 deletes" in r.output

    r = runner.invoke(cli, ["diff"])
    assert "+++ points:feature:50" in r.output
    assert "--- points:feature:2" in r.output
    assert "+                                   rating = 9.5" in r.output

    r = runner.invoke(cli, ["diff", "-o", "json"])
    features = json.loads(r.output)["kart.diff/v1+hexwkb"]["points"]["feature"]
    assert len(features) == 3

    # diff with filter
    r = runner.invoke(cli, ["diff", "points:50"])
    assert "points:feature:50" in r.output
    assert "points:feature:2" not in r.output

    r = runner.invoke(cli, ["commit", "-m", "three changes"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["status"])
    assert "working copy clean" in r.output

    r = runner.invoke(cli, ["log", "--oneline"])
    assert "three changes" in r.output.splitlines()[0]


def test_commit_nothing_fails(repo_dir, runner):
    r = runner.invoke(cli, ["commit", "-m", "empty"])
    assert r.exit_code != 0
    assert "No changes" in r.output


def test_diff_between_commits(repo_dir, runner):
    wc_edit(repo_dir, "UPDATE points SET name = 'x' WHERE fid = 4;")
    runner.invoke(cli, ["commit", "-m", "edit"])
    r = runner.invoke(cli, ["diff", "HEAD^...HEAD"])
    assert "points:feature:4" in r.output
    # two-dot (merge-base) form
    r = runner.invoke(cli, ["diff", "HEAD^..HEAD"])
    assert "points:feature:4" in r.output
    # quiet form exit codes
    r = runner.invoke(cli, ["diff", "--exit-code", "HEAD^...HEAD"])
    assert r.exit_code == 1
    r = runner.invoke(cli, ["diff", "--exit-code", "HEAD...HEAD"])
    assert r.exit_code == 0


def test_show_and_create_patch_and_apply(repo_dir, runner):
    wc_edit(repo_dir, "UPDATE points SET name = 'patched' WHERE fid = 5;")
    runner.invoke(cli, ["commit", "-m", "patchable"])
    r = runner.invoke(cli, ["show"])
    assert "patchable" in r.output and "points:feature:5" in r.output

    r = runner.invoke(cli, ["create-patch", "HEAD"])
    patch = json.loads(r.output)
    assert "kart.patch/v1" in patch
    assert patch["kart.patch/v1"]["message"].startswith("patchable")

    # revert, then re-apply the patch
    runner.invoke(cli, ["reset", "--discard-changes", "HEAD^"])
    patch_path = repo_dir / "p.json"
    patch_path.write_text(json.dumps(patch))
    r = runner.invoke(cli, ["apply", str(patch_path)])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["show"])
    assert "points:feature:5" in r.output


def test_branch_checkout_switch(repo_dir, runner):
    r = runner.invoke(cli, ["checkout", "-b", "dev"])
    assert "Switched to a new branch 'dev'" in r.output
    wc_edit(repo_dir, "UPDATE points SET name = 'dev-edit' WHERE fid = 1;")
    runner.invoke(cli, ["commit", "-m", "dev work"])
    r = runner.invoke(cli, ["branch"])
    assert "* dev" in r.output and "  main" in r.output

    r = runner.invoke(cli, ["switch", "main"])
    assert r.exit_code == 0, r.output
    # WC reflects main now
    con = sqlite3.connect(repo_dir / "wc.gpkg")
    name = con.execute("SELECT name FROM points WHERE fid = 1").fetchone()[0]
    con.close()
    assert name == "feature-1"

    r = runner.invoke(cli, ["switch", "dev"])
    con = sqlite3.connect(repo_dir / "wc.gpkg")
    name = con.execute("SELECT name FROM points WHERE fid = 1").fetchone()[0]
    con.close()
    assert name == "dev-edit"


def test_checkout_dirty_refuses(repo_dir, runner):
    runner.invoke(cli, ["checkout", "-b", "dev"])
    runner.invoke(cli, ["switch", "main"])
    wc_edit(repo_dir, "UPDATE points SET name = 'dirty' WHERE fid = 1;")
    r = runner.invoke(cli, ["checkout", "dev"])
    assert r.exit_code != 0
    # force works
    r = runner.invoke(cli, ["checkout", "--force", "dev"])
    assert r.exit_code == 0, r.output


def test_restore(repo_dir, runner):
    wc_edit(repo_dir, "UPDATE points SET name = 'scratch' WHERE fid = 1;")
    r = runner.invoke(cli, ["restore"])
    assert r.exit_code == 0, r.output
    con = sqlite3.connect(repo_dir / "wc.gpkg")
    name = con.execute("SELECT name FROM points WHERE fid = 1").fetchone()[0]
    con.close()
    assert name == "feature-1"
    r = runner.invoke(cli, ["status"])
    assert "working copy clean" in r.output


def test_tag(repo_dir, runner):
    runner.invoke(cli, ["tag", "v1.0", "-m", "first release"])
    r = runner.invoke(cli, ["tag"])
    assert "v1.0" in r.output
    r = runner.invoke(cli, ["show", "v1.0", "-o", "json"])
    assert r.exit_code == 0
    runner.invoke(cli, ["tag", "-d", "v1.0"])
    r = runner.invoke(cli, ["tag"])
    assert "v1.0" not in r.output


def test_fsck(repo_dir, runner):
    r = runner.invoke(cli, ["fsck"])
    assert r.exit_code == 0, r.output
    assert "No errors found" in r.output


def test_geojson_diff(repo_dir, runner):
    wc_edit(repo_dir, "UPDATE points SET name = 'gj' WHERE fid = 3;")
    r = runner.invoke(cli, ["diff", "-o", "geojson"])
    fc = json.loads(r.output)
    assert fc["type"] == "FeatureCollection"
    ids = [f["id"] for f in fc["features"]]
    assert "U-::3" in ids and "U+::3" in ids


def test_json_lines_diff(repo_dir, runner):
    wc_edit(repo_dir, "DELETE FROM points WHERE fid = 9;")
    r = runner.invoke(cli, ["diff", "-o", "json-lines"])
    lines = [json.loads(line) for line in r.output.strip().splitlines()]
    assert lines[0]["type"] == "version"
    feature_lines = [l for l in lines if l["type"] == "feature"]
    assert len(feature_lines) == 1
    assert feature_lines[0]["change"]["-"]["fid"] == 9


def test_diff_crs_reprojection(repo_dir, runner):
    wc_edit(repo_dir, "UPDATE points SET name = 'moved' WHERE fid = 1;")
    r = runner.invoke(cli, ["diff", "-o", "json", "--crs", "EPSG:3857"])
    assert r.exit_code == 0, r.output
    features = json.loads(r.output)["kart.diff/v1+hexwkb"]["points"]["feature"]
    hexwkb = features[0]["+"]["geom"]
    from kart_tpu.geometry import Geometry

    g = Geometry.from_hex_wkb(hexwkb)
    coords = g.to_coords().payload
    # lon 101 deg -> ~11.2M metres in web mercator
    assert abs(coords[0] - 11243259.18) < 1000


def test_config(repo_dir, runner):
    r = runner.invoke(cli, ["config", "user.name"])
    assert r.output.strip() == "Tester"
    runner.invoke(cli, ["config", "custom.key", "hello"])
    r = runner.invoke(cli, ["config", "custom.key"])
    assert r.output.strip() == "hello"


def test_query_bbox(repo_dir, runner):
    r = runner.invoke(
        cli,
        ["query", "HEAD", "points", "--bbox", "100,-45,105.5,-39", "-o", "json"],
    )
    assert r.exit_code == 0, r.output
    out = json.loads(r.output)["kart.query/v2"]
    # points at x=101..110: fids 1..5 are <= 105.5
    assert out["count"] == 5
    assert [f["fid"] for f in out["features"]] == [1, 2, 3, 4, 5]


def test_query_where(repo_dir, runner):
    r = runner.invoke(
        cli, ["query", "HEAD", "points", "--where", "fid = 3", "-o", "json"]
    )
    assert r.exit_code == 0, r.output
    out = json.loads(r.output)["kart.query/v2"]
    assert out["count"] == 1
    assert out["features"][0]["name"] == "feature-3"
    # default output is the count document
    r = runner.invoke(cli, ["query", "HEAD", "points", "--where", "fid > 7"])
    assert r.exit_code == 0, r.output
    assert json.loads(r.output)["kart.query/v2"]["count"] == 3


def test_query_bad_bbox(repo_dir, runner):
    r = runner.invoke(cli, ["query", "HEAD", "points", "--bbox", "nope"])
    assert r.exit_code != 0
    assert "W,S,E,N" in r.output


def test_query_bad_where(repo_dir, runner):
    r = runner.invoke(
        cli, ["query", "HEAD", "points", "--where", "nosuch = 1"]
    )
    assert r.exit_code != 0
    assert "no column" in r.output


def test_gpkg_wc_spatial_index(repo_dir, runner):
    """Checkout builds the standard gpkg_rtree_index extension (rtree
    virtual table + sync triggers), and our own sessions keep it in sync
    (reference: gpkgAddSpatialIndex, kart/working_copy/gpkg.py:432-476)."""
    con = sqlite3.connect(repo_dir / "wc.gpkg")
    # index exists and covers every non-null geometry
    n = con.execute('SELECT count(*) FROM "rtree_points_geom"').fetchone()[0]
    assert n == 10
    ext = con.execute(
        "SELECT extension_name, scope FROM gpkg_extensions "
        "WHERE table_name = 'points'"
    ).fetchone()
    assert ext == ("gpkg_rtree_index", "write-only")
    # a bbox query through the rtree finds the right features (x = 101..110)
    hits = sorted(
        r[0]
        for r in con.execute(
            'SELECT id FROM "rtree_points_geom" WHERE maxx >= 102.5 AND minx <= 104.5'
        )
    )
    assert hits == [3, 4]
    con.close()

    # commits applied through kart keep the index in sync (our sessions
    # register the ST_* functions the spec triggers call)
    wc_edit(repo_dir, "DELETE FROM points WHERE fid = 3;")
    r = runner.invoke(cli, ["commit", "-m", "delete 3"])
    assert r.exit_code == 0, r.output
    con = sqlite3.connect(repo_dir / "wc.gpkg")
    ids = {r[0] for r in con.execute('SELECT id FROM "rtree_points_geom"')}
    assert 3 not in ids and len(ids) == 9
    con.close()


def test_reflog(repo_dir, runner):
    wc_edit(repo_dir, "DELETE FROM points WHERE fid = 1;")
    r = runner.invoke(cli, ["commit", "-m", "delete 1"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["reflog", "main"])
    assert r.exit_code == 0, r.output
    lines = r.output.strip().splitlines()
    assert len(lines) >= 2
    assert "main@{0}" in lines[0] and "delete 1" in lines[0]
    r = runner.invoke(cli, ["reflog"])
    assert r.exit_code == 0, r.output
    assert "HEAD@{0}" in r.output


def test_commit_message_from_editor(repo_dir, runner, monkeypatch):
    """Without -m, the commit message comes from $EDITOR; '#' template lines
    are stripped and an empty message aborts."""
    wc_edit(repo_dir, "DELETE FROM points WHERE fid = 7;")
    editor = repo_dir / "fake-editor.sh"
    editor.write_text('#!/bin/sh\necho "editor message" > "$1"\n')
    editor.chmod(0o755)
    monkeypatch.setenv("EDITOR", str(editor))
    monkeypatch.setenv("VISUAL", str(editor))
    r = runner.invoke(cli, ["commit"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["log"])
    assert "editor message" in r.output

    # empty message aborts
    wc_edit(repo_dir, "DELETE FROM points WHERE fid = 8;")
    editor.write_text('#!/bin/sh\nprintf "# only comments\\n" > "$1"\n')
    r = runner.invoke(cli, ["commit"])
    assert r.exit_code != 0
    assert "empty commit message" in r.output


def test_commit_files(repo_dir, runner, tmp_path):
    """kart commit-files commits arbitrary repo files (attachments, docs)."""
    r = runner.invoke(
        cli, ["commit-files", "-m", "add docs", "points/ABOUT.txt=hello"]
    )
    assert r.exit_code == 0, r.output
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(str(repo_dir))
    tree = repo.structure("HEAD").tree
    assert tree.get("points/ABOUT.txt").data == b"hello"

    # @file values and removal
    payload = tmp_path / "payload.bin"
    payload.write_bytes(b"\x00\x01binary")
    r = runner.invoke(
        cli, ["commit-files", "-m", "binary", f"points/blob.bin=@{payload}"]
    )
    assert r.exit_code == 0, r.output
    r = runner.invoke(
        cli,
        ["commit-files", "-m", "rm", "--remove-empty-files", "points/ABOUT.txt="],
    )
    assert r.exit_code == 0, r.output
    repo = KartRepo(str(repo_dir))
    tree = repo.structure("HEAD").tree
    assert tree.get_or_none("points/ABOUT.txt") is None
    assert tree.get("points/blob.bin").data == b"\x00\x01binary"

    # no-op refuses without --allow-empty
    r = runner.invoke(cli, ["commit-files", "-m", "noop", "points/blob.bin=@" + str(payload)])
    assert r.exit_code != 0


def test_git_passthrough(repo_dir, runner, capfd):
    """kart git runs system git against the repo — a live interop proof
    that the object store, refs, and packs are git-compatible. git writes
    to the real fds, hence capfd."""
    import shutil

    if shutil.which("git") is None:
        pytest.skip("no system git")
    r = runner.invoke(cli, ["git", "rev-parse", "HEAD"])
    assert r.exit_code == 0
    from kart_tpu.core.repo import KartRepo

    assert capfd.readouterr().out.strip() == KartRepo(str(repo_dir)).head_commit_oid
    r = runner.invoke(cli, ["git", "cat-file", "-t", "HEAD"])
    assert r.exit_code == 0
    assert capfd.readouterr().out.strip() == "commit"


def test_commit_files_preserves_wc_edits_and_validates(repo_dir, runner):
    """An uncommitted feature edit must survive commit-files (review
    finding: force-reset wiped it), and malformed keys are rejected before
    a corrupt tree is written."""
    wc_edit(repo_dir, "UPDATE points SET name = 'keepme' WHERE fid = 6;")
    r = runner.invoke(cli, ["commit-files", "-m", "docs", "ABOUT.txt=hi"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["diff"])
    assert "keepme" in r.output  # edit survived

    for bad in ("=x", "a//b=x", "../evil=x", "a/.=x"):
        r = runner.invoke(cli, ["commit-files", "-m", "bad", bad])
        assert r.exit_code != 0, bad

    # tags must never be silently repointed
    runner.invoke(cli, ["tag", "vtag"])
    r = runner.invoke(cli, ["commit-files", "-m", "x", "--ref", "vtag", "a=b"])
    assert r.exit_code != 0


def test_reference_e2e_flow(tmp_path, runner, monkeypatch):
    """The reference's e2e-1.sh flow with its own e2e.gpkg: init -> import
    -> branch -> raw-SQL insert -> status -> diff --crs -> commit -> switch
    -> merge --no-ff -> log."""
    import shutil

    from conftest import REF_DATA
    from helpers import wc_connect

    src_gpkg = os.path.join(REF_DATA, "e2e.gpkg")
    if not os.path.exists(src_gpkg):
        pytest.skip("reference fixtures not available")

    repo_dir = tmp_path / "test"
    r = runner.invoke(
        cli, ["init", str(repo_dir), "--workingcopy-location", "test.gpkg"]
    )
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(repo_dir)
    from kart_tpu.core.repo import KartRepo

    KartRepo(".").config.set_many(
        {"user.name": "Kart E2E Test 1", "user.email": "kart-e2e@example.com"}
    )
    gpkg_copy = tmp_path / "e2e.gpkg"
    shutil.copy(src_gpkg, gpkg_copy)
    r = runner.invoke(cli, ["import", str(gpkg_copy), "--dest-path", "mylayer"])
    if r.exit_code != 0:  # --dest-path flag name may differ; import as-is
        r = runner.invoke(cli, ["import", str(gpkg_copy)])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["log"])
    assert r.exit_code == 0 and "Import" in r.output
    (ds_path,) = [
        line.strip() for line in runner.invoke(cli, ["data", "ls"]).output.splitlines()
    ]

    r = runner.invoke(cli, ["switch", "-c", "edit-1"])
    assert r.exit_code == 0, r.output

    table = ds_path.replace("/", "__")
    con = wc_connect(repo_dir / "test.gpkg")
    geom_col = [
        row[1] for row in con.execute(
            "SELECT table_name, column_name FROM gpkg_geometry_columns"
        ) if row[0] == table
    ][0]
    # GP header (empty envelope) + WKB polygon, like the script's EWKT insert
    import struct

    wkb = struct.pack("<BII", 1, 3, 1) + struct.pack("<I", 5) + b"".join(
        struct.pack("<dd", *pt) for pt in [(0, 0), (1, 0), (1, 1), (0, 1), (0, 0)]
    )
    gp = b"GP\x00\x01" + struct.pack("<i", 0) + wkb
    con.execute(
        f'INSERT INTO "{table}" (fid, "{geom_col}") VALUES (999, ?)', (gp,)
    )
    con.commit()
    con.close()

    r = runner.invoke(cli, ["status"])
    assert "1 inserts" in r.output
    r = runner.invoke(cli, ["diff", "--crs", "EPSG:3857"])
    assert r.exit_code == 0, r.output
    assert ":feature:999" in r.output
    r = runner.invoke(cli, ["commit", "-m", "my-commit"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["switch", "main"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["status"])
    assert "clean" in r.output
    r = runner.invoke(cli, ["merge", "edit-1", "--no-ff", "-m", "merge-1"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["log", "--oneline"])
    assert "merge-1" in r.output.splitlines()[0]


class TestLogOptions:
    """Reference log option surface (/root/reference/kart/log.py): date,
    author, grep, skip filters, --graph, --with-dataset-changes."""

    @pytest.fixture
    def multi_commit_repo(self, repo_dir, runner):
        """repo_dir + two more commits (an edit and a second layer)."""
        from helpers import edit_commit
        from kart_tpu.core.repo import KartRepo

        repo = KartRepo(str(repo_dir))
        edit_commit(repo, "points", updates=[{"fid": 1, "geom": None, "name": "edited-1", "rating": 0.5}],
                    message="edit point 1")
        gpkg2 = create_points_gpkg(str(repo_dir.parent / "l2.gpkg"), n=3)
        import shutil, sqlite3

        con = sqlite3.connect(gpkg2)
        con.execute("UPDATE gpkg_contents SET table_name='second' WHERE 1")
        try:
            con.execute("ALTER TABLE points RENAME TO second")
            con.execute("UPDATE gpkg_geometry_columns SET table_name='second'")
            con.commit()
        finally:
            con.close()
        r = runner.invoke(cli, ["import", str(gpkg2), "--no-checkout"])
        assert r.exit_code == 0, r.output
        return repo_dir

    def test_skip_and_max_count(self, multi_commit_repo, runner):
        r = runner.invoke(cli, ["log", "--oneline"])
        assert r.exit_code == 0, r.output
        all_lines = r.output.strip().splitlines()
        assert len(all_lines) == 3
        r = runner.invoke(cli, ["log", "--oneline", "--skip", "1", "-n", "1"])
        assert r.exit_code == 0, r.output
        assert r.output.strip().splitlines() == [all_lines[1]]

    def test_grep_and_author(self, multi_commit_repo, runner):
        r = runner.invoke(cli, ["log", "--oneline", "--grep", "edit point"])
        assert r.exit_code == 0, r.output
        assert len(r.output.strip().splitlines()) == 1
        r = runner.invoke(cli, ["log", "--oneline", "--author", "Nobody"])
        assert r.exit_code == 0, r.output
        assert r.output.strip() == ""
        r = runner.invoke(cli, ["log", "--oneline", "--author", "Tester"])
        assert len(r.output.strip().splitlines()) == 3

    def test_since_until(self, multi_commit_repo, runner):
        r = runner.invoke(cli, ["log", "--oneline", "--since", "2000-01-01"])
        assert r.exit_code == 0, r.output
        assert len(r.output.strip().splitlines()) == 3
        r = runner.invoke(cli, ["log", "--oneline", "--until", "2000-01-01"])
        assert r.exit_code == 0, r.output
        assert r.output.strip() == ""
        r = runner.invoke(cli, ["log", "--oneline", "--since", "1 day ago"])
        assert len(r.output.strip().splitlines()) == 3
        r = runner.invoke(cli, ["log", "--oneline", "--since", "not-a-date"])
        assert r.exit_code != 0
        assert "Cannot parse" in r.output

    def test_dataset_filter_and_changes(self, multi_commit_repo, runner):
        # pathspec filter: only commits touching 'second'
        r = runner.invoke(cli, ["log", "--oneline", "second"])
        assert r.exit_code == 0, r.output
        assert len(r.output.strip().splitlines()) == 1
        # feature-level filter: only commits touching points:feature:1
        r = runner.invoke(cli, ["log", "--oneline", "points:feature:1"])
        assert r.exit_code == 0, r.output
        assert len(r.output.strip().splitlines()) == 2  # import + edit
        # dataset changes listing
        r = runner.invoke(
            cli, ["log", "-o", "json", "--with-dataset-changes", "-n", "1"]
        )
        assert r.exit_code == 0, r.output
        item = json.loads(r.output)[0]
        assert item["datasetChanges"] == ["second"]

    def test_graph_linear(self, multi_commit_repo, runner):
        r = runner.invoke(cli, ["log", "--graph"])
        assert r.exit_code == 0, r.output
        lines = r.output.strip().splitlines()
        assert len(lines) == 3
        assert all(line.startswith("* ") for line in lines)

    def test_graph_merge(self, multi_commit_repo, runner):
        from kart_tpu.core.repo import KartRepo

        r = runner.invoke(cli, ["branch", "side", "HEAD^"])
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, ["checkout", "side"])
        assert r.exit_code == 0, r.output
        from helpers import edit_commit

        edit_commit(KartRepo("."), "points", updates=[{"fid": 2, "geom": None, "name": "side-2", "rating": 0.25}],
                    message="side edit")
        r = runner.invoke(cli, ["checkout", "main"])
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, ["merge", "side", "-m", "merge side"])
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, ["log", "--graph"])
        assert r.exit_code == 0, r.output
        out = r.output
        assert "\\" in out  # fork row after the merge commit
        stars = [l for l in out.splitlines() if "*" in l]
        assert len(stars) == 5  # import, edit, second, side edit, merge
        # first-parent walk hides the side branch
        r = runner.invoke(cli, ["log", "--oneline", "--first-parent"])
        assert r.exit_code == 0, r.output
        assert all("side edit" not in l for l in r.output.splitlines())


class TestLogGraphFiltered:
    def test_graph_with_filtered_commits_no_phantom_lanes(self, repo_dir, runner):
        """Filtered-out commits must not leave dangling lanes (review r4):
        with a --grep that hides the middle commit, the graph stays one
        column wide."""
        from helpers import edit_commit
        from kart_tpu.core.repo import KartRepo

        repo = KartRepo(str(repo_dir))
        edit_commit(repo, "points",
                    updates=[{"fid": 1, "geom": None, "name": "mid", "rating": 0.5}],
                    message="middle edit")
        edit_commit(repo, "points",
                    updates=[{"fid": 2, "geom": None, "name": "top", "rating": 0.5}],
                    message="top edit")
        r = runner.invoke(cli, ["log", "--graph", "--grep", "edit|Import|import"])
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, ["log", "--graph", "--grep", "top|mport"])
        assert r.exit_code == 0, r.output
        lines = [l for l in r.output.splitlines() if l.strip()]
        assert len(lines) == 2
        # single column: no phantom '|' from the hidden middle commit
        assert all(l.startswith("* ") and " | " not in l for l in lines)

    def test_typod_revision_still_errors(self, repo_dir, runner):
        r = runner.invoke(cli, ["log", "mybrnch"])
        assert r.exit_code != 0
        assert "No such revision or dataset" in r.output


def test_e2e_remote_round_trip(tmp_path, runner, monkeypatch):
    """The remote leg of the reference's e2e journey (test_e2e.py: remote
    add -> push -> clone -> edit -> push -> pull), all through the CLI over
    the local transport with working copies on both ends."""
    gpkg = create_points_gpkg(str(tmp_path / "source.gpkg"), n=8)
    origin = tmp_path / "origin"
    r = runner.invoke(cli, ["init", str(origin), "--workingcopy-location", "wc.gpkg"])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(origin)
    from kart_tpu.core.repo import KartRepo

    KartRepo(".").config.set_many(
        {"user.name": "Origin", "user.email": "o@example.com"}
    )
    r = runner.invoke(cli, ["import", str(gpkg)])
    assert r.exit_code == 0, r.output

    # bare hub remote + push
    hub = tmp_path / "hub"
    r = runner.invoke(cli, ["init", "--bare", str(hub)])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["remote", "add", "myremote", str(hub)])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["push", "--set-upstream", "myremote", "main"])
    assert r.exit_code == 0, r.output

    # clone from the hub with a working copy
    clone_dir = tmp_path / "clone"
    r = runner.invoke(cli, ["clone", str(hub), str(clone_dir)])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(clone_dir)
    KartRepo(".").config.set_many(
        {"user.name": "Cloner", "user.email": "c@example.com"}
    )
    r = runner.invoke(cli, ["log", "--oneline"])
    assert r.exit_code == 0 and len(r.output.strip().splitlines()) == 1

    # edit in the clone's WC, commit, push back to the hub
    from helpers import wc_connect

    wc = next(clone_dir.glob("*.gpkg"))
    con = wc_connect(wc)
    con.execute("UPDATE points SET name = 'from-clone' WHERE fid = 2")
    con.commit()
    con.close()
    r = runner.invoke(cli, ["commit", "-m", "clone edit"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["push"])
    assert r.exit_code == 0, r.output

    # original pulls the clone's edit; its WC reflects it
    monkeypatch.chdir(origin)
    r = runner.invoke(cli, ["pull", "myremote", "main"])
    assert r.exit_code == 0, r.output
    ds = KartRepo(".").structure("HEAD").datasets["points"]
    assert ds.get_feature([2])["name"] == "from-clone"
    con = wc_connect(origin / "wc.gpkg")
    try:
        (name,) = con.execute(
            "SELECT name FROM points WHERE fid = 2"
        ).fetchone()
    finally:
        con.close()
    assert name == "from-clone"


def test_fsck_verifies_sidecars(tmp_path, runner, monkeypatch):
    """fsck must rebuild the sidecar columns from the feature tree and fail
    loudly on a corrupted sidecar (a silent mismatch would wrong every
    columnar diff)."""
    import glob

    import kart_tpu.importer.importer as importer_mod

    monkeypatch.setattr(importer_mod, "SIDECAR_MIN_FEATURES", 5)
    gpkg = create_points_gpkg(str(tmp_path / "s.gpkg"), n=30)
    repo_dir = tmp_path / "repo"
    r = runner.invoke(cli, ["init", str(repo_dir)])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(repo_dir)
    from kart_tpu.core.repo import KartRepo

    KartRepo(".").config.set_many({"user.name": "t", "user.email": "t@e"})
    r = runner.invoke(cli, ["import", str(gpkg), "--no-checkout"])
    assert r.exit_code == 0, r.output

    r = runner.invoke(cli, ["fsck"])
    assert r.exit_code == 0, r.output
    assert "sidecar OK (30 rows)" in r.output

    # corrupt one byte of the oid columns
    (sidecar_file,) = glob.glob(str(repo_dir / ".kart" / "columnar" / "*"))
    data = bytearray(open(sidecar_file, "rb").read())
    data[-10] ^= 0xFF
    open(sidecar_file, "wb").write(bytes(data))
    r = runner.invoke(cli, ["fsck"])
    assert r.exit_code != 0
    assert "sidecar" in r.output


def test_log_with_feature_count(repo_dir, runner):
    """--with-feature-count adds per-dataset changed-feature counts to JSON
    output (reference: log.py --with-feature-count)."""
    wc_edit(repo_dir, "UPDATE points SET name = 'x' WHERE fid IN (1, 2, 3);")
    r = runner.invoke(cli, ["commit", "-m", "three edits"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(
        cli, ["log", "-o", "json", "--with-feature-count", "exact"]
    )
    assert r.exit_code == 0, r.output
    items = json.loads(r.output)
    assert items[0]["featureChanges"] == {"points": 3}
    assert items[1]["featureChanges"] == {"points": 10}  # the import
    # estimation accuracies work too
    r = runner.invoke(
        cli, ["log", "-o", "json", "--with-feature-count", "veryfast", "-n", "1"]
    )
    assert r.exit_code == 0, r.output
    assert "featureChanges" in json.loads(r.output)[0]


def test_log_feature_count_respects_filters(repo_dir, runner):
    """featureChanges must cover only the filtered datasets (review r4)."""
    gpkg2 = create_points_gpkg(str(repo_dir.parent / "l2.gpkg"), n=3)
    con = sqlite3.connect(gpkg2)
    con.execute("UPDATE gpkg_contents SET table_name='second'")
    con.execute("ALTER TABLE points RENAME TO second")
    con.execute("UPDATE gpkg_geometry_columns SET table_name='second'")
    con.commit()
    con.close()
    r = runner.invoke(cli, ["import", str(gpkg2), "--no-checkout"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(
        cli,
        ["log", "-o", "json", "--with-feature-count", "exact", "points"],
    )
    assert r.exit_code == 0, r.output
    for item in json.loads(r.output):
        assert set(item["featureChanges"]) <= {"points"}, item


def test_text_diff_byte_parity_with_reference(tmp_path, runner, monkeypatch):
    """Replicates the reference's test_diff.py text-output scenario on its
    own points fixture — pk rename (paired via find_renames), update with
    nulls, delete, insert — and asserts the EXACT expected lines from
    /root/reference/tests/test_diff.py:63-88, byte for byte (column
    alignment, the U+2400 null glyph, POINT(...) elision, rename pairing)."""
    from conftest import REF_DATA, extract_ref_archive

    if not os.path.isdir(REF_DATA):
        pytest.skip("reference fixtures not available")
    from kart_tpu.core.repo import KartRepo

    repo_path = extract_ref_archive(tmp_path, "points.tgz")
    monkeypatch.chdir(repo_path)
    KartRepo(".").config.set_many({"user.name": "t", "user.email": "t@e"})
    r = runner.invoke(cli, ["create-workingcopy", "wc.gpkg"])
    assert r.exit_code == 0, r.output

    from helpers import wc_connect

    L = "nz_pa_points_topo_150k"
    con = wc_connect(os.path.join(repo_path, "wc.gpkg"))
    # H.POINTS.RECORD from the reference conftest: fid 9999 at POINT(0 0)
    import struct

    gp = (
        b"GP\x00\x01" + struct.pack("<i", 4326)
        + struct.pack("<BI2d", 1, 1, 0.0, 0.0)
    )
    con.execute(
        f'INSERT INTO "{L}" (fid, geom, t50_fid, name_ascii, macronated, name)'
        " VALUES (9999, ?, 9999999, 'Te Motu-a-kore', 'N', 'Te Motu-a-kore')",
        (gp,),
    )
    con.execute(f'UPDATE "{L}" SET fid=9998 WHERE fid=1')
    con.execute(f'UPDATE "{L}" SET name=\'test\', t50_fid=NULL WHERE fid=2')
    con.execute(f'DELETE FROM "{L}" WHERE fid=3')
    con.commit()
    con.close()

    r = runner.invoke(cli, ["diff", "--output-format=text", "--output=-"])
    assert r.exit_code == 0, r.output
    assert r.output.splitlines() == [
        f"--- {L}:feature:1",
        f"+++ {L}:feature:9998",
        "-                                      fid = 1",
        "+                                      fid = 9998",
        f"--- {L}:feature:2",
        f"+++ {L}:feature:2",
        "-                                  t50_fid = 2426272",
        "+                                  t50_fid = ␀",
        "-                                     name = ␀",
        "+                                     name = test",
        f"--- {L}:feature:3",
        "-                                      fid = 3",
        "-                                     geom = POINT(...)",
        "-                                  t50_fid = 2426273",
        "-                               name_ascii = Tauwhare Pa",
        "-                               macronated = N",
        "-                                     name = Tauwhare Pa",
        f"+++ {L}:feature:9999",
        "+                                      fid = 9999",
        "+                                     geom = POINT(...)",
        "+                                  t50_fid = 9999999",
        "+                               name_ascii = Te Motu-a-kore",
        "+                               macronated = N",
        "+                                     name = Te Motu-a-kore",
    ]

    # geojson: same scenario, the reference's id scheme and feature set
    # (test_diff.py:110-175): U-/U+ pairs, D, I, 6 features total
    r = runner.invoke(cli, ["diff", "--output-format=geojson", "--output=-"])
    assert r.exit_code == 0, r.output
    odata = json.loads(r.output)
    ids = [f["id"] for f in odata["features"]]
    assert ids == ["U-::1", "U+::9998", "U-::2", "U+::2", "D::3", "I::9999"]
    by_id = {f["id"]: f for f in odata["features"]}
    assert by_id["I::9999"]["geometry"]["coordinates"] == [0.0, 0.0]
    assert by_id["U+::2"]["properties"]["name"] == "test"
    assert by_id["U+::2"]["properties"]["t50_fid"] is None
    assert by_id["U-::1"]["properties"]["fid"] == 1
    assert by_id["U+::9998"]["properties"]["fid"] == 9998


def test_import_list_and_all_tables(tmp_path, runner):
    """`kart import --list` enumerates source tables (text + json shapes);
    -a/--all-tables is accepted and mutually exclusive with --table
    (reference: kart/init.py --list/--all-tables options)."""
    from helpers import create_points_gpkg

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=3)
    r = runner.invoke(cli, ["init", str(tmp_path / "repo")])
    assert r.exit_code == 0, r.output
    args = ["-C", str(tmp_path / "repo")]
    r = runner.invoke(cli, [*args, "import", "--list", gpkg])
    assert r.exit_code == 0 and r.output.strip() == "points - points title"
    r = runner.invoke(cli, [*args, "import", "--list", "-o", "json", gpkg])
    body = json.loads(r.output)
    assert body == {"kart.tables/v1": {"points": "points title"}}
    r = runner.invoke(cli, [*args, "import", "--list", "-t", "points", gpkg])
    assert r.exit_code != 0
    r = runner.invoke(cli, [*args, "import", "-a", "-t", "points", gpkg])
    assert r.exit_code != 0
    r = runner.invoke(cli, [*args, "import", "-a", gpkg, "--no-checkout"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, [*args, "data", "ls"])
    assert "points" in r.output


def test_commit_json_output(tmp_path, runner):
    """`kart commit -o json` emits the reference kart.commit/v1 envelope
    (reference: kart/commit.py:263-281)."""
    import sqlite3

    from helpers import create_points_gpkg
    from kart_tpu.workingcopy.gpkg import _register_gpkg_functions

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=5)
    r = runner.invoke(cli, ["init", str(tmp_path / "repo")])
    assert r.exit_code == 0, r.output
    args = ["-C", str(tmp_path / "repo")]
    r = runner.invoke(cli, [*args, "import", gpkg])
    assert r.exit_code == 0, r.output
    wc = next(p for p in os.listdir(tmp_path / "repo") if p.endswith(".gpkg"))
    con = sqlite3.connect(tmp_path / "repo" / wc)
    _register_gpkg_functions(con)
    con.execute("UPDATE points SET name='edited' WHERE fid=2")
    con.commit()
    con.close()
    r = runner.invoke(cli, [*args, "commit", "-m", "json commit", "-o", "json"])
    assert r.exit_code == 0, r.output
    body = json.loads(r.output)["kart.commit/v1"]
    assert body["branch"] == "main"
    assert body["message"].startswith("json commit")
    assert body["abbrevCommit"] == body["commit"][:7]
    assert body["changes"]["points"]["feature"] == {"updates": 1}
    assert body["commitTime"].endswith("Z")


def test_import_primary_key_override(tmp_path, runner):
    """--primary-key re-keys the imported dataset on an existing column
    (reference: kart/init.py --primary-key)."""
    from helpers import create_attributes_gpkg

    gpkg = create_attributes_gpkg(str(tmp_path / "r.gpkg"))
    r = runner.invoke(cli, ["init", str(tmp_path / "repo")])
    assert r.exit_code == 0, r.output
    args = ["-C", str(tmp_path / "repo")]
    r = runner.invoke(
        cli, [*args, "import", gpkg, "--primary-key", "code", "--no-checkout"]
    )
    assert r.exit_code == 0, r.output
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(str(tmp_path / "repo"))
    ds = repo.structure("HEAD").datasets["records"]
    pk_cols = [c.name for c in ds.schema.pk_columns]
    assert pk_cols == ["code"]
    f = ds.get_feature(["C002"])
    assert f["code"] == "C002" and f["amount"] == 200

    r = runner.invoke(
        cli, [*args, "import", gpkg, "--primary-key", "nope", "--no-checkout"]
    )
    # ImportSourceError propagates so the entrypoint maps it to the
    # documented NO_IMPORT_SOURCE exit code (CliRunner surfaces it raw)
    assert r.exit_code != 0
    assert "no column named" in str(r.exception)


def test_apply_ref_option(tmp_path, runner):
    """`kart apply --ref` lands the patch commit on another branch, leaving
    HEAD and the working copy untouched (reference: kart/apply.py --ref)."""
    from helpers import create_points_gpkg

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=5)
    r = runner.invoke(cli, ["init", str(tmp_path / "repo")])
    assert r.exit_code == 0, r.output
    args = ["-C", str(tmp_path / "repo")]
    r = runner.invoke(cli, [*args, "import", gpkg, "--no-checkout"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, [*args, "branch", "side"])
    assert r.exit_code == 0, r.output

    patch = {
        "kart.diff/v1+hexwkb": {
            "points": {
                "feature": [
                    {"-": None, "+": None}  # placeholder replaced below
                ]
            }
        },
        "kart.patch/v1": {"message": "patched on side", "base": None},
    }
    # a real update delta for fid 2
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(str(tmp_path / "repo"))
    ds = repo.structure("HEAD").datasets["points"]
    old = ds.get_feature([2])
    new = dict(old)
    new["name"] = "patched"
    to_json = lambda f: {
        k: (v.to_hex_wkb() if hasattr(v, "to_hex_wkb") else v)
        for k, v in f.items()
    }
    patch["kart.diff/v1+hexwkb"]["points"]["feature"] = [
        {"-": to_json(old), "+": to_json(new)}
    ]
    pfile = tmp_path / "p.json"
    pfile.write_text(json.dumps(patch))
    head_before = repo.head_commit_oid
    r = runner.invoke(cli, [*args, "apply", "--ref", "side", str(pfile)])
    assert r.exit_code == 0, r.output
    repo = KartRepo(str(tmp_path / "repo"))
    assert repo.head_commit_oid == head_before  # HEAD untouched
    side_ds = repo.structure("refs/heads/side").datasets["points"]
    assert side_ds.get_feature([2])["name"] == "patched"
    # --ref + --no-commit refuse
    r = runner.invoke(
        cli, [*args, "apply", "--ref", "side", "--no-commit", str(pfile)]
    )
    assert r.exit_code != 0


def test_apply_ref_edge_cases(tmp_path, runner):
    """--ref on the checked-out branch takes the HEAD path (working copy
    rolls forward); tags are refused."""
    import sqlite3

    from helpers import create_points_gpkg

    gpkg = create_points_gpkg(str(tmp_path / "pts.gpkg"), n=5)
    r = runner.invoke(cli, ["init", str(tmp_path / "repo")])
    args = ["-C", str(tmp_path / "repo")]
    r = runner.invoke(cli, [*args, "import", gpkg])
    assert r.exit_code == 0, r.output

    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(str(tmp_path / "repo"))
    ds = repo.structure("HEAD").datasets["points"]
    old = ds.get_feature([3])
    new = dict(old)
    new["name"] = "via-ref-main"
    to_json = lambda f: {
        k: (v.to_hex_wkb() if hasattr(v, "to_hex_wkb") else v)
        for k, v in f.items()
    }
    patch = {
        "kart.diff/v1+hexwkb": {
            "points": {"feature": [{"-": to_json(old), "+": to_json(new)}]}
        },
        "kart.patch/v1": {"message": "main patch", "base": None},
    }
    pfile = tmp_path / "p.json"
    pfile.write_text(json.dumps(patch))

    r = runner.invoke(cli, [*args, "apply", "--ref", "main", str(pfile)])
    assert r.exit_code == 0, r.output
    # HEAD advanced AND the working copy rolled forward with it
    wc = next(p for p in os.listdir(tmp_path / "repo") if p.endswith(".gpkg"))
    con = sqlite3.connect(tmp_path / "repo" / wc)
    (name,) = con.execute("SELECT name FROM points WHERE fid=3").fetchone()
    con.close()
    assert name == "via-ref-main"
    r = runner.invoke(cli, [*args, "status"])
    assert r.exit_code == 0 and "clean" in r.output.lower()

    r = runner.invoke(cli, [*args, "tag", "v1"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(
        cli, [*args, "apply", "--ref", "refs/tags/v1", str(pfile)]
    )
    assert r.exit_code != 0  # tags must never be rewritten
