"""Native C++ spatial-filter core vs the numpy reference path: identical
results on the same inputs (the bit-compatibility discipline of SURVEY.md §4
applied to the native layer)."""

import numpy as np
import pytest

from kart_tpu import native
from kart_tpu.ops.bbox import bbox_intersects_np
from kart_tpu.ops.envelope_codec import EnvelopeCodec


@pytest.fixture(scope="module")
def native_lib():
    lib = native.ensure_built()
    if lib is None:
        pytest.skip("no C++ toolchain available to build the native library")
    return lib


def _random_envelopes(n, rng):
    w = rng.uniform(-180, 180, n)
    e = np.clip(w + rng.uniform(0, 20, n), -180, 180)
    s = rng.uniform(-90, 89, n)
    n_ = np.clip(s + rng.uniform(0, 10, n), -90, 90)
    return np.stack([w, s, e, n_], axis=1)


def test_decode_matches_codec(native_lib):
    rng = np.random.default_rng(42)
    envs = _random_envelopes(500, rng)
    codec = EnvelopeCodec()
    packed = codec.encode_batch(envs)

    native_decoded = native.decode_envelopes(packed)
    numpy_decoded = codec.decode_batch(packed)
    np.testing.assert_allclose(native_decoded, numpy_decoded, rtol=0, atol=1e-12)


def test_bbox_intersects_matches_numpy(native_lib):
    rng = np.random.default_rng(7)
    envs = _random_envelopes(2000, rng)
    query = (100.0, -45.0, 120.0, -35.0)
    np.testing.assert_array_equal(
        native.bbox_intersects(envs, query), bbox_intersects_np(envs, query)
    )


def test_bbox_antimeridian(native_lib):
    envs = np.array(
        [
            [175.0, 0.0, 176.0, 1.0],  # near the anti-meridian, west side
            [-176.0, 0.0, -175.0, 1.0],  # east side
            [170.0, 0.0, -170.0, 1.0],  # an envelope crossing it
            [0.0, 0.0, 10.0, 1.0],  # far away
        ]
    )
    query = (170.0, -5.0, -170.0, 5.0)  # query crossing the anti-meridian
    expected = bbox_intersects_np(envs, query)
    np.testing.assert_array_equal(native.bbox_intersects(envs, query), expected)
    assert list(expected) == [True, True, True, False]


def test_filter_packed_fused_path(native_lib):
    rng = np.random.default_rng(3)
    envs = _random_envelopes(1000, rng)
    codec = EnvelopeCodec()
    packed = codec.encode_batch(envs)
    query = (-10.0, -10.0, 10.0, 10.0)

    fused = native.filter_packed(packed, query)
    # reference: decode (with codec quantisation) then intersect
    expected = bbox_intersects_np(codec.decode_batch(packed), query)
    np.testing.assert_array_equal(fused, expected)


def test_numpy_fallback_when_lib_absent(monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", True)
    rng = np.random.default_rng(1)
    envs = _random_envelopes(100, rng)
    query = (0.0, -50.0, 50.0, 0.0)
    np.testing.assert_array_equal(
        native.bbox_intersects(envs, query), bbox_intersects_np(envs, query)
    )


class TestNativeIO:
    def test_pack_objects_batch_matches_hashlib(self):
        import hashlib
        import zlib

        from kart_tpu import native

        if native.load_io() is None:
            native.ensure_built()
        if native.load_io() is None:
            pytest.skip("native IO lib not built")
        contents = [b"hello", b"", b"x" * 70000, b"hello"]
        oids, streams = native.pack_objects_batch("blob", contents, level=1)
        for i, content in enumerate(contents):
            header = b"blob %d\x00" % len(content)
            assert bytes(oids[i]) == hashlib.sha1(header + content).digest()
            assert zlib.decompress(streams[i]) == content

    def test_add_batch_matches_per_object_path(self, tmp_path, monkeypatch):
        """Native and Python pack-writing produce identical object ids and
        readable packs."""
        from kart_tpu import native
        from kart_tpu.core.packs import Packfile, PackWriter

        contents = [b"alpha", b"beta" * 1000, b"", b"alpha"]

        with PackWriter(str(tmp_path / "native")) as w1:
            native_oids = w1.add_batch("blob", contents)

        monkeypatch.setattr(native, "pack_objects_batch", lambda *a, **k: None)
        with PackWriter(str(tmp_path / "python")) as w2:
            python_oids = w2.add_batch("blob", contents)

        assert native_oids == python_oids
        # dedupe preserved: 'alpha' twice -> one entry
        assert w1._count == w2._count == 3
        pack = Packfile(w1.pack_path, w1.idx_path)
        for oid, content in zip(native_oids, contents):
            assert pack.read(bytes.fromhex(oid)) == ("blob", content)


class TestTreeDiffRaw:
    def _tree(self, entries):
        from kart_tpu.core.objects import TreeEntry, serialise_tree

        return serialise_tree(
            [TreeEntry(n, m, o) for n, m, o in entries]
        )

    def test_matches_python_walk(self):
        from kart_tpu import native
        from kart_tpu.core.objects import MODE_BLOB, MODE_TREE, parse_tree

        if native.load_io() is None:
            import pytest

            pytest.skip("native IO lib unavailable")

        def oid(i):
            return f"{i:040x}"

        a = self._tree(
            [
                ("a.txt", MODE_BLOB, oid(1)),
                ("b.txt", MODE_BLOB, oid(2)),
                ("subdir", MODE_TREE, oid(3)),
                ("z.txt", MODE_BLOB, oid(4)),
            ]
        )
        b = self._tree(
            [
                ("a.txt", MODE_BLOB, oid(1)),  # unchanged
                ("b.txt", MODE_BLOB, oid(22)),  # modified
                ("c.txt", MODE_BLOB, oid(5)),  # added
                ("subdir", MODE_TREE, oid(33)),  # subtree changed
                # z.txt deleted
            ]
        )
        rows = native.tree_diff_raw(a, b)
        assert rows is not None
        got = {r[0]: r[1:] for r in rows}
        assert set(got) == {"b.txt", "c.txt", "subdir", "z.txt"}
        assert got["b.txt"] == (oid(2), oid(22), False, False)
        assert got["c.txt"] == (None, oid(5), False, False)
        assert got["subdir"] == (oid(3), oid(33), True, True)
        assert got["z.txt"] == (oid(4), None, False, False)
        # identical trees -> no rows
        assert native.tree_diff_raw(a, a) == []

    def test_random_trees_match_python_reference(self):
        import random

        from kart_tpu import native
        from kart_tpu.core.objects import MODE_BLOB, MODE_TREE, parse_tree

        if native.load_io() is None:
            import pytest

            pytest.skip("native IO lib unavailable")
        rng = random.Random(7)
        for _ in range(50):
            names = [f"n{rng.randrange(40):02d}" for _ in range(rng.randrange(1, 30))]
            names = sorted(set(names))

            def entries():
                out = []
                for n in names:
                    if rng.random() < 0.8:
                        mode = MODE_TREE if rng.random() < 0.3 else MODE_BLOB
                        out.append((n, mode, f"{rng.randrange(2**32):040x}"))
                return out

            a_entries, b_entries = entries(), entries()
            a, b = self._tree(a_entries), self._tree(b_entries)
            rows = native.tree_diff_raw(a, b)
            assert rows is not None
            # python reference: dict compare
            da = {(n, m == MODE_TREE): o for n, m, o in a_entries}
            db = {(n, m == MODE_TREE): o for n, m, o in b_entries}
            want = {}
            for key in set(da) | set(db):
                name, is_tree = key
                oa, ob = da.get(key), db.get(key)
                if oa == ob:
                    continue
                want[(name, is_tree)] = (oa, ob)
            got = {}
            for name, oa, ob, at, bt in rows:
                # rows where a and b types differ arrive as two entries or
                # one combined; normalise into the same keyed form
                if oa is not None:
                    got.setdefault((name, at), [None, None])[0] = oa
                if ob is not None:
                    got.setdefault((name, bt), [None, None])[1] = ob
            got = {k: tuple(v) for k, v in got.items()}
            assert got == want, (a_entries, b_entries)

    def test_malformed_tree_returns_none(self):
        from kart_tpu import native

        if native.load_io() is None:
            import pytest

            pytest.skip("native IO lib unavailable")
        assert native.tree_diff_raw(b"garbage without nul", b"") is None


def test_bbox_f32_matches_numpy_reference():
    """The new f32 sidecar-scan kernel agrees with the numpy reference on
    random envelopes including antimeridian-wrapping ranges and queries."""
    import numpy as np

    from kart_tpu.native import bbox_intersects_f32, load
    from kart_tpu.ops.bbox import bbox_intersects_np

    rng = np.random.default_rng(11)
    n = 40_000
    env = np.empty((n, 4), dtype=np.float32)
    env[:, 0] = rng.uniform(-180, 180, n)  # w
    env[:, 1] = rng.uniform(-90, 89, n)    # s
    width = rng.uniform(0, 30, n)
    env[:, 2] = env[:, 0] + width          # e (some wrap past 180)
    env[(env[:, 2] > 180), 2] -= 360.0     # wrapping ranges: e < w
    env[:, 3] = np.minimum(env[:, 1] + rng.uniform(0, 20, n), 90)

    queries = [
        (-40.0, -20.0, -4.0, -3.0),
        (170.0, -50.0, -170.0, 10.0),   # query wraps the antimeridian
        (-180.0, -90.0, 180.0, 90.0),   # whole world
        (12.25, 47.5, 12.26, 47.51),    # tiny box
    ]
    for q in queries:
        got = bbox_intersects_f32(env, q)
        want = bbox_intersects_np(env.astype(np.float64), np.asarray(q))
        np.testing.assert_array_equal(got, want, err_msg=str(q))
    if load() is None:
        pytest.skip("native lib absent: exercised the fallback only")


class TestLeafPayloadKernel:
    """io_leaf_payloads (the import pipeline's native leaf-tree build) must
    be bit-identical to the numpy plan path (StreamingLeafEmitter's
    fallback) across msgpack width boundaries and leaf shapes."""

    def _ref(self, enc, pks, oids):
        from kart_tpu.core.feature_tree import StreamingLeafEmitter

        em = StreamingLeafEmitter(enc)
        em._native = False  # force the numpy plan path
        return em._payloads(np.asarray(pks, np.int64), oids)

    @pytest.mark.parametrize(
        "name,pks",
        [
            ("dense", list(range(5000))),
            ("fixint_edge", list(range(100, 300))),          # crosses 0x7F
            ("u8_u16_edge", list(range(200, 70000, 37))),    # 0xFF / 0xFFFF
            ("single", [0]),
            ("one_leaf", list(range(64, 128))),
        ],
    )
    def test_matches_python_plan_path(self, name, pks):
        from kart_tpu import native
        from kart_tpu.models.paths import PathEncoder

        if native.load_io() is None:
            pytest.skip("native IO lib unavailable")
        enc = PathEncoder.INT_PK_ENCODER
        limit = enc.branches ** (enc.levels + 1)
        rng = np.random.default_rng(5)
        pks = np.asarray(pks, dtype=np.int64)
        oids = rng.integers(0, 256, (len(pks), 20), dtype=np.uint8)
        nat = native.leaf_payloads(pks, oids, enc.branches, limit)
        assert nat is not None
        buf_r, off_r, lid_r = self._ref(enc, pks, oids)
        np.testing.assert_array_equal(nat[2], lid_r, err_msg=name)
        np.testing.assert_array_equal(nat[1], off_r, err_msg=name)
        assert bytes(np.asarray(nat[0])) == bytes(np.asarray(buf_r)), name

    def test_sparse_random_pks_match(self):
        from kart_tpu import native
        from kart_tpu.models.paths import PathEncoder

        if native.load_io() is None:
            pytest.skip("native IO lib unavailable")
        enc = PathEncoder.INT_PK_ENCODER
        limit = enc.branches ** (enc.levels + 1)
        rng = np.random.default_rng(6)
        pks = np.sort(
            rng.choice(limit - 1, 4000, replace=False)
        ).astype(np.int64)
        oids = rng.integers(0, 256, (len(pks), 20), dtype=np.uint8)
        nat = native.leaf_payloads(pks, oids, enc.branches, limit)
        buf_r, off_r, lid_r = self._ref(enc, pks, oids)
        np.testing.assert_array_equal(nat[2], lid_r)
        assert bytes(np.asarray(nat[0])) == bytes(np.asarray(buf_r))

    def test_rejects_out_of_contract_pks(self):
        """Unordered / negative / over-limit pks -> None (the caller falls
        back to the plan path, which handles them via max_trees wrap)."""
        from kart_tpu import native
        from kart_tpu.models.paths import PathEncoder

        if native.load_io() is None:
            pytest.skip("native IO lib unavailable")
        enc = PathEncoder.INT_PK_ENCODER
        limit = enc.branches ** (enc.levels + 1)
        z = np.zeros((2, 20), np.uint8)
        br = enc.branches
        assert native.leaf_payloads(
            np.array([5, 3], np.int64), z, br, limit) is None
        assert native.leaf_payloads(
            np.array([-1, 3], np.int64), z, br, limit) is None
        assert native.leaf_payloads(
            np.array([0, limit], np.int64), z, br, limit) is None
