"""Golden-SQL parity for the server-database working copies.

Live PostGIS / MySQL / SQL Server instances aren't available in this
environment (those tests skip), so the SQL each dialect emits — create
table, change-tracking triggers, CRS registration, checkout upsert, state/
track bookkeeping — is snapshotted against golden files instead, and the
type mappings are asserted directly against the expectations derived from
the reference's adapters (kart/sqlalchemy/adapter/{postgis,mysql,
sqlserver}.py V2_TYPE_TO_SQL_TYPE tables).

Regenerate the goldens after an intentional SQL change with:

    KART_REGEN_GOLDEN=1 python -m pytest tests/test_workingcopy_golden_sql.py
"""

import os

import pytest

from kart_tpu.adapters.mysql import MySqlAdapter
from kart_tpu.adapters.postgis import PostgisAdapter
from kart_tpu.adapters.sqlserver import SqlServerAdapter
from kart_tpu.models.schema import ColumnSchema, Schema

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def _col(name, data_type, pk_index=None, **extra):
    return ColumnSchema(
        id=f"00000000-0000-4000-8000-{abs(hash(name)) % 10**12:012d}",
        name=name,
        data_type=data_type,
        pk_index=pk_index,
        extra_type_info=extra,
    )


# one column per V2 data type / size variant the adapters must map
WIDE_SCHEMA = Schema(
    [
        _col("fid", "integer", pk_index=0, size=64),
        _col("geom", "geometry", geometryType="POINT", geometryCRS="EPSG:4326"),
        _col("flag", "boolean"),
        _col("payload", "blob"),
        _col("born", "date"),
        _col("ratio32", "float", size=32),
        _col("ratio64", "float", size=64),
        _col("tiny", "integer", size=8),
        _col("small", "integer", size=16),
        _col("med", "integer", size=32),
        _col("amount", "numeric", precision=10, scale=2),
        _col("name", "text"),
        _col("code", "text", length=40),
        _col("at_time", "time"),
        _col("seen_utc", "timestamp", timezone="UTC"),
        _col("seen_naive", "timestamp"),
    ]
)

ADAPTERS = {
    "postgis": PostgisAdapter,
    "mysql": MySqlAdapter,
    "sqlserver": SqlServerAdapter,
}


def _stmts(value):
    """Adapters return a statement string or a list of them."""
    if value is None:
        return []
    if isinstance(value, str):
        return [value]
    return list(value)


def emit_dialect_sql(adapter):
    """Everything the dialect says to the server for a canonical dataset."""
    out = []
    db_schema = "kartwc"
    table = "wide_table"

    out.append("-- column specs (v2 schema -> SQL)")
    for col in WIDE_SCHEMA.columns:
        spec = adapter.v2_column_schema_to_sql_spec(
            col, has_int_pk=True, crs_id=4326
        )
        out.append(f"{spec}")

    out.append("")
    out.append("-- base DDL (kart_state / kart_track / trigger support)")
    for stmt in _stmts(adapter.base_ddl(db_schema)):
        out.append(stmt.strip() + ";")

    out.append("")
    out.append("-- change-tracking triggers")
    for stmt in _stmts(adapter.create_trigger_sql(db_schema, table, "fid")):
        out.append(stmt.strip() + ";")
    for stmt in _stmts(adapter.drop_trigger_sql(db_schema, table)):
        out.append(stmt.strip() + ";")

    out.append("")
    out.append("-- CRS registration")
    stmt = adapter.register_crs_sql(4326, "EPSG", 4326, "GEOGCS[...]")
    if stmt:
        sql = stmt[0] if isinstance(stmt, tuple) else stmt
        out.append(str(sql).strip() + ";")

    out.append("")
    out.append("-- checkout upsert")
    upsert = adapter.upsert_sql(
        db_schema,
        table,
        [c.name for c in WIDE_SCHEMA.columns],
        ["fid"],
        crs_id=4326,
        schema=WIDE_SCHEMA,
    )
    out.append(str(upsert).strip() + ";")
    return "\n".join(out) + "\n"


@pytest.mark.parametrize("name", sorted(ADAPTERS))
def test_golden_sql(name):
    adapter = ADAPTERS[name]
    got = emit_dialect_sql(adapter)
    path = os.path.join(GOLDEN_DIR, f"{name}_wc.sql")
    if os.environ.get("KART_REGEN_GOLDEN") == "1":
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(got)
        pytest.skip(f"regenerated {path}")
    assert os.path.exists(path), (
        f"golden file missing; run KART_REGEN_GOLDEN=1 pytest {__file__}"
    )
    with open(path) as f:
        want = f.read()
    assert got == want, (
        f"{name} working-copy SQL changed; diff against {path} and "
        f"regenerate with KART_REGEN_GOLDEN=1 if intentional"
    )


# -- type-mapping parity with the reference adapters ------------------------
# expectations transcribed from the reference's V2_TYPE_TO_SQL_TYPE tables
# (kart/sqlalchemy/adapter/postgis.py:29-47, mysql.py:28-46,
# sqlserver.py:52-70)

REFERENCE_TYPE_MAP = {
    "postgis": {
        "flag": "BOOLEAN",
        "payload": "BYTEA",
        "born": "DATE",
        "ratio32": "REAL",
        "ratio64": "DOUBLE PRECISION",
        "tiny": "SMALLINT",  # approximated, like the reference
        "small": "SMALLINT",
        "med": "INTEGER",
        "fid": "BIGINT",
        "name": "TEXT",
        "code": "VARCHAR(40)",
        "at_time": "TIME",
        "seen_utc": "TIMESTAMPTZ",
        "seen_naive": "TIMESTAMP",
        "amount": "NUMERIC(10,2)",
    },
    "mysql": {
        "flag": "BIT",
        "payload": "LONGBLOB",
        "born": "DATE",
        "ratio32": "FLOAT",
        "ratio64": "DOUBLE PRECISION",
        "tiny": "TINYINT",
        "small": "SMALLINT",
        "med": "INT",
        "fid": "BIGINT",
        "name": "LONGTEXT",
        "at_time": "TIME",
        "seen_utc": "TIMESTAMP",
        "seen_naive": "DATETIME",
        "amount": "NUMERIC(10,2)",
    },
    "sqlserver": {
        "flag": "BIT",
        "payload": "VARBINARY(max)",
        "born": "DATE",
        "ratio32": "REAL",
        "ratio64": "FLOAT",
        "tiny": "TINYINT",
        "small": "SMALLINT",
        "med": "INT",
        "fid": "BIGINT",
        "at_time": "TIME",
        "seen_utc": "DATETIMEOFFSET",
        "seen_naive": "DATETIME2",
        "amount": "NUMERIC(10,2)",
    },
}


@pytest.mark.parametrize("name", sorted(REFERENCE_TYPE_MAP))
def test_type_mapping_matches_reference(name):
    adapter = ADAPTERS[name]
    cols = {c.name: c for c in WIDE_SCHEMA.columns}
    for col_name, want in REFERENCE_TYPE_MAP[name].items():
        got = adapter.v2_type_to_sql_type(cols[col_name])
        assert got.upper() == want.upper(), (
            f"{name}.{col_name}: {got!r} != reference {want!r}"
        )
