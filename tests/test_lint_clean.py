"""Tier-1 gate (ISSUE 4): `kart lint` is clean at HEAD and stays fast.

This is the enforcement half of the static-analysis suite — the golden
corpus (tests/test_analysis.py) proves the rules *can* fire; this test
proves they *don't* on the shipped tree, so every cross-cutting contract
(env vars, telemetry grammar, fault points, resource lifecycle, thread/fork
safety, exception hygiene, bench schema) is machine-verified on every run.
"""

import time

from kart_tpu import analysis


def test_lint_clean_at_head():
    report = analysis.run_lint()
    assert report.ok, "kart lint found:\n" + analysis.to_text(report)
    # the full default target set actually ran (not a silently-empty scan)
    assert report.files_scanned >= 100
    assert "bench.py" in report.scanned
    assert "kart_tpu/core/repo.py" in report.scanned


def test_rule_catalogue_complete():
    ids = {r["id"] for r in analysis.rule_catalogue()}
    # 7 contract rules (ISSUE 4) + 5 concurrency rules + 2 device rules
    # (ISSUE 11) + 5 taint rules (ISSUE 19) + KTL000 suppression hygiene
    # + KTL099 parse-error
    assert ids == (
        {f"KTL00{i}" for i in range(8)}
        | {"KTL010", "KTL011", "KTL012", "KTL013", "KTL014"}
        | {"KTL020", "KTL021"}
        | {"KTL030", "KTL031", "KTL032", "KTL033", "KTL034"}
        | {"KTL099"}
    )


def test_per_rule_timings_recorded():
    """ISSUE 11 satellite: the report attributes wall-clock per rule, so
    the <5s bound stays diagnosable as the rule count grows."""
    report = analysis.run_lint()
    assert set(report.rule_seconds) == {
        r["id"] for r in report.rules
    } - {"KTL000", "KTL099"}
    assert all(v >= 0.0 for v in report.rule_seconds.values())
    assert sum(report.rule_seconds.values()) < 5.0


def test_lint_runs_under_five_seconds():
    """The ISSUE 4 performance bound: whole tree + bench.py in <5s on CPU
    (measured ~2.2s; bench.py records the exact number as
    lint_runtime_seconds)."""
    t0 = time.perf_counter()
    analysis.run_lint()
    assert time.perf_counter() - t0 < 5.0
