import os
import subprocess

import pytest

from kart_tpu.core.objects import MODE_BLOB
from kart_tpu.core.repo import KartRepo, KartRepoState, NotFound
from kart_tpu.core.tree_builder import TreeBuilder


@pytest.fixture
def repo(tmp_path):
    r = KartRepo.init_repository(tmp_path / "r")
    r.config.set_many({"user.name": "Tester", "user.email": "t@example.com"})
    return r


def make_commit(repo, files, message, ref="HEAD", parents=None):
    tb = TreeBuilder(repo.odb, repo.head_tree_oid if parents is None else None)
    if parents is None:
        parents = [repo.head_commit_oid] if repo.head_commit_oid else []
    for path, content in files.items():
        tb.insert(path, repo.odb.write_blob(content))
    tree = tb.flush()
    return repo.create_commit(ref, tree, message, parents)


def test_init_and_reopen(tmp_path):
    r = KartRepo.init_repository(tmp_path / "x")
    assert r.state == KartRepoState.NORMAL
    assert r.head_is_unborn
    assert r.version == 3
    r2 = KartRepo(tmp_path / "x")
    assert r2.gitdir == r.gitdir
    # opening from a subdirectory finds the repo
    os.makedirs(tmp_path / "x" / "sub")
    assert KartRepo(tmp_path / "x" / "sub").gitdir == r.gitdir


def test_init_refuses_double(tmp_path):
    KartRepo.init_repository(tmp_path / "x")
    with pytest.raises(Exception):
        KartRepo.init_repository(tmp_path / "x")


def test_commit_and_resolve(repo):
    c1 = make_commit(repo, {"a.txt": b"one\n"}, "first")
    c2 = make_commit(repo, {"b.txt": b"two\n"}, "second")
    assert repo.head_commit_oid == c2
    assert repo.resolve_refish("HEAD") == (c2, "refs/heads/main")
    assert repo.resolve_refish("main")[0] == c2
    assert repo.resolve_refish("HEAD~1")[0] == c1
    assert repo.resolve_refish("HEAD^")[0] == c1
    assert repo.resolve_refish(c1)[0] == c1
    assert repo.resolve_refish(c1[:8])[0] == c1
    assert repo.resolve_refish("HEAD^?")[0] == c1
    assert repo.resolve_refish("[EMPTY]") == (None, None)
    # ^? on root commit -> empty
    assert repo.resolve_refish(f"{c1}^?")[0] is None
    with pytest.raises(NotFound):
        repo.resolve_refish("nope")


def test_walk_and_merge_base(repo):
    c1 = make_commit(repo, {"a": b"1"}, "c1")
    c2 = make_commit(repo, {"b": b"2"}, "c2")
    # branch from c1
    repo.refs.set("refs/heads/feature", c1)
    tb = TreeBuilder(repo.odb, repo.odb.read_commit(c1).tree)
    tb.insert("c", repo.odb.write_blob(b"3"))
    c3 = repo.create_commit("refs/heads/feature", tb.flush(), "c3", [c1])

    assert repo.merge_base(c2, c3) == c1
    assert repo.is_ancestor(c1, c2)
    assert not repo.is_ancestor(c2, c3)
    oids = [oid for oid, _ in repo.walk_commits(c2)]
    assert oids == [c2, c1]


def test_tags(repo):
    c1 = make_commit(repo, {"a": b"1"}, "c1")
    repo.create_tag("v-light", c1)
    tag_oid = repo.create_tag("v-annot", c1, message="release")
    assert repo.resolve_refish("v-light")[0] == c1
    assert repo.resolve_refish("v-annot")[0] == c1  # peeled through tag object
    assert repo.odb.read_tag(tag_oid).name == "v-annot"


def test_git_interop(repo, tmp_path):
    """Real git can read everything we write."""
    c1 = make_commit(repo, {"a.txt": b"one\n", "dir/b.txt": b"two\n"}, "first")
    c2 = make_commit(repo, {"a.txt": b"ONE\n"}, "second")
    # the locked kart index blocks even read-only git commands unless we point
    # git at a scratch index (that refusal is itself asserted below)
    env = {
        **os.environ,
        "GIT_DIR": repo.gitdir,
        "GIT_INDEX_FILE": str(tmp_path / "scratch-index"),
    }

    out = subprocess.run(
        ["git", "fsck", "--strict"], env=env, capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr

    log = subprocess.run(
        ["git", "log", "--format=%H %s"], env=env, capture_output=True, text=True
    ).stdout.splitlines()
    assert log == [f"{c2} second", f"{c1} first"]

    show = subprocess.run(
        ["git", "show", "HEAD~1:dir/b.txt"], env=env, capture_output=True, text=True
    ).stdout
    assert show == "two\n"

    # the locked index makes stock git refuse worktree operations
    locked_env = {**os.environ, "GIT_DIR": repo.gitdir, "GIT_WORK_TREE": repo.workdir}
    status = subprocess.run(
        ["git", "status"], env=locked_env, capture_output=True, text=True
    )
    assert status.returncode != 0
    assert "kart" in (status.stderr + status.stdout).lower()


def test_tree_builder_nested(repo):
    odb = repo.odb
    tb = TreeBuilder(odb)
    tb.insert("x/y/z.txt", odb.write_blob(b"deep"))
    tb.insert("top.txt", odb.write_blob(b"top"))
    t1 = tb.flush()
    view = odb.tree(t1)
    assert view["x/y/z.txt"].data == b"deep"
    assert view["top.txt"].data == b"top"

    # incremental change reuses unchanged subtrees
    tb2 = TreeBuilder(odb, t1)
    tb2.insert("x/y/w.txt", odb.write_blob(b"more"))
    t2 = tb2.flush()
    v2 = odb.tree(t2)
    assert v2["x/y/z.txt"].data == b"deep"
    assert v2["x/y/w.txt"].data == b"more"

    # removal prunes empty parents
    tb3 = TreeBuilder(odb, t2)
    tb3.remove("x/y/z.txt")
    tb3.remove("x/y/w.txt")
    t3 = tb3.flush()
    v3 = odb.tree(t3)
    assert v3.get_or_none("x") is None
    assert v3["top.txt"].data == b"top"


def test_walk_blobs(repo):
    odb = repo.odb
    tb = TreeBuilder(odb)
    tb.insert("a/1", odb.write_blob(b"1"))
    tb.insert("a/2", odb.write_blob(b"2"))
    tb.insert("b/3", odb.write_blob(b"3"))
    t = tb.flush()
    paths = [p for p, _ in odb.tree(t).walk_blobs()]
    assert paths == ["a/1", "a/2", "b/3"]


def test_config_subsections(repo):
    repo.config["remote.origin.url"] = "/some/path"
    repo.config["remote.origin.promisor"] = True
    repo2 = KartRepo(repo.workdir)
    assert repo2.remote_url("origin") == "/some/path"
    assert repo2.has_promisor_remote()
    assert repo2.remotes() == ["origin"]


def test_promised_object(repo):
    from kart_tpu.core.odb import ObjectMissing, ObjectPromised

    fake_oid = "ab" * 20
    with pytest.raises(ObjectMissing):
        repo.odb.read_blob(fake_oid)
    repo.config["remote.origin.url"] = "/x"
    repo.config["remote.origin.promisor"] = True
    repo2 = KartRepo(repo.workdir)
    with pytest.raises(ObjectPromised):
        repo2.odb.read_blob(fake_oid)


def test_reflog(repo):
    c1 = make_commit(repo, {"a": b"1"}, "c1")
    entries = repo.refs.read_reflog("refs/heads/main")
    assert len(entries) == 1
    assert entries[0]["new"] == c1
    assert "c1" in entries[0]["message"]


def test_git_fsck_on_stored_stream_packs(tmp_path):
    """Real system git must fully verify a repo whose packs were written by
    the bulk import path — which emits small payloads as STORED zlib
    streams (native io_pack_records) — proving the fast path stays inside
    the git pack format."""
    import subprocess

    from helpers import make_imported_repo

    repo, ds_path = make_imported_repo(tmp_path, n=200)
    pack_dir = os.path.join(repo.gitdir, "objects", "pack")
    assert any(f.endswith(".pack") for f in os.listdir(pack_dir))

    env = {
        **os.environ,
        "GIT_DIR": repo.gitdir,
        "GIT_INDEX_FILE": str(tmp_path / "scratch-index"),
    }
    out = subprocess.run(
        ["git", "fsck", "--strict"], env=env, capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr

    # git verify-pack checks every record's crc + inflate
    for f in os.listdir(pack_dir):
        if f.endswith(".idx"):
            out = subprocess.run(
                ["git", "verify-pack", "-v", os.path.join(pack_dir, f)],
                env=env,
                capture_output=True,
                text=True,
            )
            assert out.returncode == 0, out.stderr

    # and git can read a feature blob out of the tree
    ds = repo.structure("HEAD").datasets[ds_path]
    tree = ds.feature_tree
    out = subprocess.run(
        ["git", "ls-tree", "-r", tree.oid], env=env, capture_output=True, text=True
    )
    assert out.returncode == 0 and len(out.stdout.splitlines()) == 200


def test_gc_packs_loose_objects(tmp_path):
    """gc must repack loose objects into a packfile (reference: kart gc
    delegates to git gc) and everything must stay readable — including to
    system git."""
    import subprocess

    from helpers import edit_commit, make_imported_repo

    repo, ds_path = make_imported_repo(tmp_path, n=20)
    # a few commits create loose trees/commits/blobs alongside import packs
    for i in range(3):
        edit_commit(
            repo, ds_path,
            updates=[{"fid": 1 + i, "geom": None, "name": f"gc-{i}", "rating": 0.5}],
            message=f"edit {i}",
        )
    objects_dir = os.path.join(repo.gitdir, "objects")

    def loose_count():
        n = 0
        for prefix in os.listdir(objects_dir):
            if len(prefix) == 2:
                n += len(os.listdir(os.path.join(objects_dir, prefix)))
        return n

    before = loose_count()
    assert before > 0
    # --auto below the threshold: no-op
    stats = repo.gc("--auto")
    assert stats["packed"] == 0 and loose_count() == before
    # full gc repacks everything
    stats = repo.gc()
    assert stats["packed"] == before
    assert loose_count() == 0
    # all history still readable
    ds = repo.structure("HEAD").datasets[ds_path]
    assert ds.get_feature([1])["name"] == "gc-0"
    assert repo.structure("HEAD~3").datasets[ds_path].get_feature([1])["name"] == "feature-1"
    env = {
        **os.environ,
        "GIT_DIR": repo.gitdir,
        "GIT_INDEX_FILE": str(tmp_path / "scratch-index"),
    }
    out = subprocess.run(
        ["git", "fsck", "--strict"], env=env, capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr
