"""EXECUTE the server-DB working copies against fake DBAPI drivers.

VERDICT r3 missing #1: the PostGIS / MySQL / SQL Server working copies had
never executed anywhere — golden files prove emission stability, the
dialect checker proves validity, but no code path had actually *run*. These
tests inject stateful fake drivers (sys.modules) and drive the real
``create_and_initialise`` + ``write_full`` checkout: base DDL, CRS
registration, table creation, batched feature inserts with per-dialect
value conversion, trigger creation, and the state-table tree round trip —
every statement the backend issues is recorded AND validated in its SQL
dialect by tests/sql_dialect_check.py."""

import re
import sys

import pytest

from helpers import make_imported_repo
from sql_dialect_check import MSSQL, MYSQL, PG, check_sql


class FakeServerCursor:
    def __init__(self, con):
        self.con = con
        self._rows = []

    def execute(self, sql, params=()):
        self.con.statements.append((sql, params))
        self._rows = self.con.respond(sql, params)
        return self

    def executemany(self, sql, rows):
        self.con.statements.append((sql, None))
        self.con.many_counts.setdefault(" ".join(sql.split()), 0)
        self.con.many_counts[" ".join(sql.split())] += len(rows)
        self.con.many_rows.setdefault(" ".join(sql.split()), []).extend(rows)
        self._rows = []
        return self

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return list(self._rows)

    def __iter__(self):
        return iter(self._rows)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def close(self):
        pass


class FakeServerCon:
    """Recording fake with just enough state for the WC lifecycle: tracks
    whether the container exists, which tables were created, and emulates
    the _kart_state tree row."""

    def __init__(self, driver):
        self.driver = driver

    @property
    def statements(self):
        return self.driver.statements

    @property
    def many_counts(self):
        return self.driver.many_counts

    @property
    def many_rows(self):
        return self.driver.many_rows

    def cursor(self, *a, **kw):
        return FakeServerCursor(self)

    def commit(self):
        pass

    def rollback(self):
        pass

    def close(self):
        pass

    def respond(self, sql, params):
        d = self.driver
        text = " ".join(sql.split()).lower()
        if text.startswith(("create schema", "create database")) or (
            text.startswith(("if schema_id", "exec"))
        ):
            d.container_created = True
            return []
        if text.startswith("create table"):
            m = re.search(r'create table (?:if not exists )?([^ (]+)', text)
            if m:
                d.tables.add(m.group(1).strip('"`[]'))
            return []
        if text.startswith("drop table"):
            return []
        # state-table emulation
        if "_kart_state" in text:
            if text.startswith("delete"):
                d.state.pop(("*", "tree"), None)
                return []
            if text.startswith("insert"):
                d.state[("*", "tree")] = params[0]
                return []
            if text.startswith("select value"):
                v = d.state.get(("*", "tree"))
                return [(v,)] if v is not None else []
        # existence probes
        if "schemata" in text or "sys.schemas" in text or "schema_name" in text:
            return [(1,)] if d.container_created else []
        if "count(*)" in text and "tables" in text:
            n = len([t for t in d.tables if "_kart_" not in t])
            return [(n,)]
        if "information_schema" in text or "geometry_columns" in text:
            return []
        return []


class FakeServerDriver:
    def __init__(self):
        self.statements = []
        self.many_counts = {}
        self.many_rows = {}
        self.state = {}
        self.tables = set()
        self.container_created = False

    def connect(self, *a, **kw):
        return FakeServerCon(self)

    # psycopg2 compatibility surface some code probes
    class extensions:
        pass


CASES = [
    (
        "postgis",
        "pymodule:psycopg2",
        "postgresql://db.example.com/gis/wcschema",
        PG,
    ),
    ("mysql", "pymodule:pymysql", "mysql://db.example.com/wcdb", MYSQL),
    (
        "sqlserver",
        "pymodule:pyodbc",
        "mssql://db.example.com/gis/wcschema",
        MSSQL,
    ),
]


@pytest.mark.parametrize("name,module,location,dialect", CASES)
def test_full_checkout_executes_and_validates(
    tmp_path, monkeypatch, name, module, location, dialect
):
    repo, ds_path = make_imported_repo(tmp_path, n=25)
    driver = FakeServerDriver()
    monkeypatch.setitem(sys.modules, module.split(":")[1], driver)
    repo.config["kart.workingcopy.location"] = location

    from kart_tpu.workingcopy import get_working_copy

    wc = get_working_copy(repo, allow_uncreated=True)
    assert wc is not None, location
    wc.create_and_initialise()
    assert driver.container_created

    structure = repo.structure("HEAD")
    ds = structure.datasets[ds_path]
    wc.write_full(structure, ds)

    # the state table round-trips the checked-out tree
    assert wc.get_db_tree() == structure.tree_oid
    wc.assert_db_tree_match(structure.tree_oid)

    # all 25 features inserted through the batched path
    (insert_sql, n) = next(
        (k, v) for k, v in driver.many_counts.items() if k.startswith("INSERT")
    )
    assert n == 25
    rows = driver.many_rows[insert_sql]
    assert len(rows[0]) == 4  # fid, geom, name, rating

    # trigger DDL actually executed
    trigger_stmts = [
        s for s, _ in driver.statements if "TRIGGER" in s.upper()
    ]
    assert trigger_stmts, "no trigger DDL executed"

    # EVERY executed statement is valid in the backend's SQL dialect
    for sql, _params in driver.statements:
        stmt = sql.strip().rstrip(";")
        # parameter placeholders appear where the driver interpolates
        check_sql(stmt + ";", dialect)
    for sql in driver.many_counts:
        check_sql(sql.strip().rstrip(";") + ";", dialect)


def test_fake_driver_rejects_wrong_dialect(tmp_path, monkeypatch):
    """The executed-statement validation has teeth: the PG statement stream
    must NOT validate as MySQL."""
    from sql_dialect_check import SqlDialectError

    repo, ds_path = make_imported_repo(tmp_path, n=5)
    driver = FakeServerDriver()
    monkeypatch.setitem(sys.modules, "psycopg2", driver)
    repo.config["kart.workingcopy.location"] = (
        "postgresql://db.example.com/gis/wcschema"
    )
    from kart_tpu.workingcopy import get_working_copy

    wc = get_working_copy(repo, allow_uncreated=True)
    wc.create_and_initialise()
    wc.write_full(repo.structure("HEAD"), repo.structure("HEAD").datasets[ds_path])
    with pytest.raises(SqlDialectError):
        for sql, _ in driver.statements:
            check_sql(sql.strip().rstrip(";") + ";", MYSQL)


def test_postgis_wc_diff_executes(tmp_path, monkeypatch):
    """The server-DB diff path itself executes: tracked pks stream from the
    fake _kart_track, WC rows convert through the PG adapter (EWKB in), and
    diff_dataset_to_working_copy yields exactly the seeded update+insert."""
    from kart_tpu.crs import WGS84_WKT

    repo, ds_path = make_imported_repo(tmp_path, n=10)
    driver = FakeServerDriver()
    monkeypatch.setitem(sys.modules, "psycopg2", driver)
    repo.config["kart.workingcopy.location"] = (
        "postgresql://db.example.com/gis/wcschema"
    )
    from kart_tpu.workingcopy import get_working_copy

    wc = get_working_copy(repo, allow_uncreated=True)
    ds = repo.structure("HEAD").datasets[ds_path]
    old3 = ds.get_feature([3])

    pg_cols = [
        ("fid", "bigint", "int8", None, 64, 0, 1),
        ("geom", "USER-DEFINED", "geometry", None, None, None, None),
        ("name", "text", "text", None, None, None, None),
        ("rating", "double precision", "float8", None, 53, None, None),
    ]
    wc_row_3 = (
        3,
        old3["geom"].to_ewkb() if old3["geom"] is not None else None,
        "edited-on-server",
        old3["rating"],
    )
    wc_row_99 = (99, None, "fresh-row", 0.5)

    base_respond = FakeServerCon.respond

    def respond(self, sql, params):
        text = " ".join(sql.split()).lower()
        if "information_schema.tables" in text:
            return [(1,)]  # the points table exists in the WC
        if "information_schema.columns c" in text:
            return pg_cols
        if text.startswith("select gc.f_geometry_column"):
            return [("geom", "POINT", 4326, WGS84_WKT)]
        if text.startswith("select srs.srtext"):
            return [(WGS84_WKT,)]
        if "_kart_track" in text and text.startswith("select pk"):
            return [("3",), ("99",)]
        if text.startswith("select") and "st_asewkb" in text:
            return [wc_row_3, wc_row_99]
        return base_respond(self, sql, params)

    monkeypatch.setattr(FakeServerCon, "respond", respond)

    diff = wc.diff_dataset_to_working_copy(ds)
    feats = diff["feature"]
    assert len(feats) == 2
    upd = feats[3]
    assert upd.type == "update"
    assert upd.new_value["name"] == "edited-on-server"
    assert upd.old_value == old3
    # geometry supplied as EWKB converted back to identical canonical form
    assert upd.new_value["geom"] == old3["geom"]
    ins = feats[99]
    assert ins.type == "insert"
    assert ins.new_value["name"] == "fresh-row"
    # every statement the diff issued validates as PostgreSQL
    for sql, _ in driver.statements:
        check_sql(sql.strip().rstrip(";") + ";", PG)


@pytest.mark.parametrize("name,module,location,dialect", CASES)
def test_incremental_reset_executes_upserts(
    tmp_path, monkeypatch, name, module, location, dialect
):
    """checkout -> commit -> reset drives the incremental path: the
    dialect's upsert (ON CONFLICT / REPLACE INTO / MERGE) executes under
    suspended triggers and the state tree advances."""
    from helpers import edit_commit

    repo, ds_path = make_imported_repo(tmp_path, n=10)
    driver = FakeServerDriver()
    monkeypatch.setitem(sys.modules, module.split(":")[1], driver)
    repo.config["kart.workingcopy.location"] = location
    from kart_tpu.workingcopy import get_working_copy

    wc = get_working_copy(repo, allow_uncreated=True)
    wc.create_and_initialise()
    head1 = repo.structure("HEAD")
    wc.write_full(head1, *head1.datasets)
    assert wc.get_db_tree() == head1.tree_oid

    edit_commit(
        repo, ds_path,
        updates=[{"fid": 4, "geom": None, "name": "reset-me", "rating": 2.5}],
        deletes=[7],
        message="server reset edit",
    )
    head2 = repo.structure("HEAD")
    driver.statements.clear()
    driver.many_counts.clear()
    wc.reset(head2)

    assert wc.get_db_tree() == head2.tree_oid
    stream = [s for s, _ in driver.statements] + list(driver.many_counts)
    upserts = [
        s
        for s in stream
        if "ON CONFLICT" in s or "REPLACE INTO" in s or s.lstrip().upper().startswith("MERGE")
    ]
    assert upserts, "no upsert statement executed during reset"
    deletes = [s for s, p in driver.statements if s.lstrip().upper().startswith("DELETE FROM") and p]
    assert deletes, "no targeted delete executed during reset"
    # triggers suspended + restored around the apply: every suspend has a
    # matching restore AFTER it in the statement stream (round-trip), and
    # the upserts execute inside the suspended window
    uppers = [s.upper() for s, _ in driver.statements]
    suspend_ix = [
        i for i, s in enumerate(uppers)
        if "DROP TRIGGER" in s or "DISABLE TRIGGER" in s
    ]
    restore_ix = [
        i for i, s in enumerate(uppers)
        if "CREATE TRIGGER" in s or "ENABLE TRIGGER" in s
    ]
    assert suspend_ix, "triggers were not suspended"
    assert restore_ix, "triggers were not restored after the apply"
    assert len(suspend_ix) == len(restore_ix), (
        "suspend/restore pair mismatch: "
        f"{len(suspend_ix)} suspends vs {len(restore_ix)} restores"
    )
    assert max(suspend_ix) < min(restore_ix), (
        "trigger restore executed before suspension completed"
    )
    # classify by statement head: a MySQL CREATE TRIGGER restore contains
    # REPLACE INTO in its body but is not itself an upsert
    upsert_ix = [
        i for i, s in enumerate(uppers)
        if (s.lstrip().startswith(("REPLACE INTO", "MERGE")))
        or (s.lstrip().startswith("INSERT") and "ON CONFLICT" in s)
    ]
    assert upsert_ix, "no upsert recorded in the positional stream"
    assert max(suspend_ix) < min(upsert_ix) and max(upsert_ix) < min(restore_ix), (
        "upserts must execute inside the trigger-suspended window"
    )
    # every statement valid in the dialect
    for s in stream:
        check_sql(s.strip().rstrip(";") + ";", dialect)
