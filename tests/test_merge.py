"""3-way merge: kernel bit-compat, fast-forward, clean merge, conflicts,
resolve, --continue/--abort, state machine (reference: tests/test_merge.py,
tests/test_conflicts.py, tests/test_resolve.py)."""

import json

import numpy as np
import pytest

from helpers import edit_commit, make_imported_repo
from kart_tpu.core.repo import InvalidOperation, KartRepoState
from kart_tpu.geometry import Geometry
from kart_tpu.merge import (
    abort_merging_state,
    complete_merging_state,
    do_merge,
)
from kart_tpu.merge.index import ConflictEntry, MergeIndex
from kart_tpu.ops.blocks import FeatureBlock
from kart_tpu.ops.merge_kernel import (
    CONFLICT,
    KEEP_OURS,
    TAKE_THEIRS,
    merge_classify,
    merge_classify_reference,
)


def _block(items):
    """{key: oid_byte} -> FeatureBlock with synthetic 20-byte oids."""
    keys = np.asarray(sorted(items), dtype=np.int64)
    oids = np.zeros((len(keys), 5), dtype=np.uint32)
    for i, k in enumerate(keys):
        oids[i, :] = items[k]
    paths = [f"p{k}" for k in keys]
    return FeatureBlock.from_arrays(keys, oids, paths)


class TestMergeKernel:
    def test_classic_rules(self):
        #       key: 1 unchanged, 2 theirs-edit, 3 ours-edit, 4 both-same-edit,
        #            5 conflict-edit, 6 theirs-delete, 7 ours-insert,
        #            8 theirs-insert, 9 both-insert-same, 10 both-insert-diff
        a = _block({1: 1, 2: 2, 3: 3, 4: 4, 5: 5, 6: 6})
        o = _block({1: 1, 2: 2, 3: 33, 4: 44, 5: 55, 6: 6, 7: 7, 9: 9, 10: 100})
        # key 6 absent from theirs (theirs-delete)
        t = _block({1: 1, 2: 22, 3: 3, 4: 44, 5: 555, 8: 8, 9: 9, 10: 101})

        union, decision, presence, stats = merge_classify(a, o, t)
        by_key = dict(zip(union.tolist(), decision.tolist()))
        assert by_key[1] == KEEP_OURS
        assert by_key[2] == TAKE_THEIRS
        assert by_key[3] == KEEP_OURS
        assert by_key[4] == KEEP_OURS  # same edit both sides
        assert by_key[5] == CONFLICT
        assert by_key[6] == TAKE_THEIRS  # theirs deleted
        assert by_key[7] == KEEP_OURS  # ours insert
        assert by_key[8] == TAKE_THEIRS  # theirs insert
        assert by_key[9] == KEEP_OURS  # same insert
        assert by_key[10] == CONFLICT  # add/add different
        assert stats["conflicts"] == 2

    def test_matches_reference(self):
        rng = np.random.default_rng(3)
        n = 500
        base = {int(k): int(v) for k, v in zip(rng.choice(5000, n, replace=False), rng.integers(1, 2**31, n))}
        ours = dict(base)
        theirs = dict(base)
        for k in list(base)[:50]:
            ours[k] = int(rng.integers(1, 2**31))
        for k in list(base)[30:80]:
            theirs[k] = int(rng.integers(1, 2**31))
        for k in list(base)[100:120]:
            del ours[k]
        for k in list(base)[110:130]:
            del theirs[k]
        a_b, o_b, t_b = _block(base), _block(ours), _block(theirs)
        union, decision, _, _ = merge_classify(a_b, o_b, t_b)
        ref_union, ref_decision = merge_classify_reference(a_b, o_b, t_b)
        assert np.array_equal(union, ref_union)
        assert np.array_equal(decision, ref_decision)


@pytest.fixture
def branched_repo(tmp_path):
    """repo with main (theirs edits) and branch 'ours' checked out."""
    repo, ds_path = make_imported_repo(tmp_path, n=10)
    base_oid = repo.head_commit_oid
    # create branch alt from base
    repo.refs.set("refs/heads/alt", base_oid)
    return repo, ds_path, base_oid


def _feature(fid, name, rating=1.0, x=100.0, y=-40.0):
    return {
        "fid": fid,
        "geom": Geometry.from_wkt(f"POINT ({x} {y})"),
        "name": name,
        "rating": rating,
    }


class TestDoMerge:
    def test_fast_forward(self, branched_repo):
        repo, ds_path, base = branched_repo
        edit_commit(repo, ds_path, inserts=[_feature(50, "new")])
        head = repo.head_commit_oid
        # reset HEAD branch back to base, then merge the edit commit
        branch = repo.head_branch
        repo.refs.set(branch, base)
        result = do_merge(repo, head)
        assert result.fast_forward
        assert repo.head_commit_oid == head

    def test_already_merged(self, branched_repo):
        repo, ds_path, base = branched_repo
        edit_commit(repo, ds_path, inserts=[_feature(50, "new")])
        result = do_merge(repo, base)
        assert result.already_merged

    def test_clean_merge(self, branched_repo):
        repo, ds_path, base = branched_repo
        # ours: edit fid 2 on main
        edit_commit(repo, ds_path, updates=[_feature(2, "ours-2", 2.0)])
        # theirs: edit fid 3 + insert 60 on alt
        edit_commit(
            repo,
            ds_path,
            updates=[_feature(3, "theirs-3", 3.0)],
            inserts=[_feature(60, "theirs-60")],
            ref="refs/heads/alt",
        )
        result = do_merge(repo, "alt")
        assert not result.has_conflicts
        assert result.commit_oid
        commit = repo.odb.read_commit(result.commit_oid)
        assert len(commit.parents) == 2
        merged = repo.datasets(result.commit_oid)[ds_path]
        assert merged.get_feature([2])["name"] == "ours-2"
        assert merged.get_feature([3])["name"] == "theirs-3"
        assert merged.get_feature([60])["name"] == "theirs-60"
        assert repo.state == KartRepoState.NORMAL

    def test_conflicting_merge_and_resolve(self, branched_repo):
        repo, ds_path, base = branched_repo
        edit_commit(repo, ds_path, updates=[_feature(4, "ours-4")])
        edit_commit(
            repo, ds_path, updates=[_feature(4, "theirs-4")], ref="refs/heads/alt"
        )
        result = do_merge(repo, "alt")
        assert result.has_conflicts
        assert repo.state == KartRepoState.MERGING
        label = f"{ds_path}:feature:4"
        assert list(result.merge_index.conflicts) == [label]

        # cannot merge again while merging
        with pytest.raises(InvalidOperation):
            do_merge(repo, "alt")
        # cannot continue while unresolved
        with pytest.raises(InvalidOperation):
            complete_merging_state(repo)

        # resolve with theirs
        merge_index = MergeIndex.read_from_repo(repo)
        aot = merge_index.conflicts[label]
        merge_index.add_resolve(label, [aot.theirs])
        merge_index.write_to_repo(repo)

        commit_oid = complete_merging_state(repo)
        assert repo.state == KartRepoState.NORMAL
        merged = repo.datasets(commit_oid)[ds_path]
        assert merged.get_feature([4])["name"] == "theirs-4"
        commit = repo.odb.read_commit(commit_oid)
        assert len(commit.parents) == 2

    def test_resolve_with_delete(self, branched_repo):
        repo, ds_path, base = branched_repo
        edit_commit(repo, ds_path, updates=[_feature(4, "ours-4")])
        edit_commit(
            repo, ds_path, updates=[_feature(4, "theirs-4")], ref="refs/heads/alt"
        )
        do_merge(repo, "alt")
        label = f"{ds_path}:feature:4"
        merge_index = MergeIndex.read_from_repo(repo)
        merge_index.add_resolve(label, [])
        merge_index.write_to_repo(repo)
        commit_oid = complete_merging_state(repo)
        merged = repo.datasets(commit_oid)[ds_path]
        with pytest.raises(KeyError):
            merged.get_feature([4])
        assert merged.feature_count == 9

    def test_abort(self, branched_repo):
        repo, ds_path, base = branched_repo
        head_before = None
        edit_commit(repo, ds_path, updates=[_feature(4, "ours-4")])
        head_before = repo.head_commit_oid
        edit_commit(
            repo, ds_path, updates=[_feature(4, "theirs-4")], ref="refs/heads/alt"
        )
        do_merge(repo, "alt")
        assert repo.state == KartRepoState.MERGING
        abort_merging_state(repo)
        assert repo.state == KartRepoState.NORMAL
        assert repo.head_commit_oid == head_before

    def test_delete_edit_conflict(self, branched_repo):
        repo, ds_path, base = branched_repo
        edit_commit(repo, ds_path, deletes=[6])
        edit_commit(
            repo, ds_path, updates=[_feature(6, "theirs-6")], ref="refs/heads/alt"
        )
        result = do_merge(repo, "alt")
        assert result.has_conflicts
        label = f"{ds_path}:feature:6"
        aot = result.merge_index.conflicts[label]
        assert aot.ours is None  # deleted in ours
        assert aot.theirs is not None
        assert aot.ancestor is not None

    def test_meta_conflict(self, branched_repo):
        repo, ds_path, base = branched_repo
        from kart_tpu.diff.structs import (
            DatasetDiff,
            Delta,
            DeltaDiff,
            KeyValue,
            RepoDiff,
        )

        def meta_commit(title, ref):
            structure = repo.structure(ref)
            meta_diff = DeltaDiff()
            meta_diff.add_delta(
                Delta.update(
                    KeyValue(("title", "points title")), KeyValue(("title", title))
                )
            )
            ds_diff = DatasetDiff()
            ds_diff["meta"] = meta_diff
            repo_diff = RepoDiff()
            repo_diff[ds_path] = ds_diff
            return structure.commit_diff(repo_diff, f"retitle {title}")

        meta_commit("ours title", "HEAD")
        meta_commit("theirs title", "refs/heads/alt")
        result = do_merge(repo, "alt")
        assert result.has_conflicts
        assert f"{ds_path}:meta:title" in result.merge_index.conflicts

    def test_merge_dry_run(self, branched_repo):
        repo, ds_path, base = branched_repo
        head_before = repo.head_commit_oid
        edit_commit(
            repo, ds_path, updates=[_feature(3, "theirs-3")], ref="refs/heads/alt"
        )
        result = do_merge(repo, "alt", dry_run=True)
        assert result.dry_run
        assert repo.head_commit_oid == head_before
        assert repo.state == KartRepoState.NORMAL


class TestConflictMaterialisation:
    """Batched conflict materialisation (BASELINE config #5 path)."""

    def _block(self, keys, oid_salt, paths):
        from kart_tpu.ops.blocks import FeatureBlock, bucket_size, PAD_KEY

        keys = np.asarray(keys, dtype=np.int64)
        n = len(keys)
        rng = np.random.default_rng(0)
        oids = rng.integers(0, 2**32, size=(n, 5), dtype=np.uint32)
        oids[:, 0] ^= oid_salt
        block = FeatureBlock.__new__(FeatureBlock)
        size = bucket_size(max(n, 1))
        if size > n:
            keys = np.concatenate([keys, np.full(size - n, PAD_KEY, np.int64)])
            oids = np.concatenate([oids, np.zeros((size - n, 5), np.uint32)])
        block.keys = keys
        block.oids = oids
        block.paths = list(paths)
        block.count = n
        return block

    def test_labels_decode_with_each_versions_encoder(self):
        """Every conflict label must decode the rel path with the encoder of
        the version the path came from — a pk-type change means versions of
        one dataset can carry different path encodings, and decoding hash
        paths with the int encoder would collapse labels (and so conflicts)."""
        from kart_tpu.merge import materialise_conflicts
        from kart_tpu.models.paths import PathEncoder
        from kart_tpu.ops.merge_kernel import CONFLICT, merge_classify

        int_enc = PathEncoder.INT_PK_ENCODER
        keys = np.arange(4, dtype=np.int64)
        int_paths = int_enc.encode_paths_batch(keys)

        class _IntDs:
            path_encoder = int_enc

            @staticmethod
            def decode_path_to_pks(rel):
                return int_enc.decode_path_to_pks(rel)

        a = self._block(keys, 0, int_paths)
        o = self._block(keys, 1, int_paths)  # every row changed in ours
        t = self._block(keys, 2, int_paths)  # ... and differently in theirs
        union, decision, _, stats = merge_classify(a, o, t)
        conflict_idx = np.nonzero(decision == CONFLICT)[0]
        assert len(conflict_idx) == 4

        conflicts = materialise_conflicts(
            "ds", [a, o, t], [_IntDs(), _IntDs(), _IntDs()], "inner",
            union, conflict_idx,
        )
        # distinct, correctly-decoded labels — one per conflicting pk
        assert sorted(conflicts) == [f"ds:feature:{k}" for k in range(4)]
        for label, aot in conflicts.items():
            assert aot.ancestor is not None
            assert aot.ours is not None and aot.theirs is not None
            assert aot.ours.path.startswith("inner/feature/")

    def test_labels_mixed_encoders_decode_real_pks(self):
        """A pk-type change leaves versions with different encoders; a
        conflict present only in the hash-keyed versions must still be
        labelled with its decoded pk, not the internal 63-bit hash key."""
        from kart_tpu.merge import materialise_conflicts
        from kart_tpu.models.paths import PathEncoder
        from kart_tpu.ops.blocks import hash_keys_for_paths
        from kart_tpu.ops.merge_kernel import CONFLICT, merge_classify

        int_enc = PathEncoder.INT_PK_ENCODER
        hash_enc = PathEncoder.GENERAL_ENCODER

        pks = [101, 202, 303]
        hash_paths = [hash_enc.encode_pks_to_path((pk,)) for pk in pks]
        order = np.argsort(hash_keys_for_paths(hash_paths))
        hash_paths = [hash_paths[i] for i in order]
        keys = np.sort(hash_keys_for_paths(hash_paths))

        class _IntDs:
            path_encoder = int_enc

        class _HashDs:
            path_encoder = hash_enc

            @staticmethod
            def decode_path_to_pks(rel):
                return hash_enc.decode_path_to_pks(rel)

        a = self._block(np.zeros(0, dtype=np.int64), 0, [])
        o = self._block(keys, 1, hash_paths)
        t = self._block(keys, 2, hash_paths)
        union, decision, _, _ = merge_classify(a, o, t)
        conflict_idx = np.nonzero(decision == CONFLICT)[0]
        assert len(conflict_idx) == 3

        conflicts = materialise_conflicts(
            "ds", [a, o, t], [_IntDs(), _HashDs(), _HashDs()], "inner",
            union, conflict_idx,
        )
        assert sorted(conflicts) == sorted(f"ds:feature:{pk}" for pk in pks)

    def test_labels_fall_back_per_version_without_encoder(self):
        """datasets=None versions still label every conflict distinctly."""
        from kart_tpu.merge import materialise_conflicts
        from kart_tpu.ops.merge_kernel import CONFLICT, merge_classify

        keys = np.arange(3, dtype=np.int64)
        paths = [f"aa/k{k}" for k in keys]
        a = self._block(keys, 0, paths)
        o = self._block(keys, 1, paths)
        t = self._block(keys, 2, paths)
        union, decision, _, _ = merge_classify(a, o, t)
        conflict_idx = np.nonzero(decision == CONFLICT)[0]
        conflicts = materialise_conflicts(
            "ds", [a, o, t], [None, None, None], "inner", union, conflict_idx
        )
        assert len(conflicts) == 3
        assert all(label.startswith("ds:feature:") for label in conflicts)


def test_merge_index_binary_roundtrip(tmp_path, monkeypatch):
    """Above the threshold MERGE_INDEX is written as the columnar binary
    format; reading detects the encoding and rebuilds identically."""
    import kart_tpu.merge.index as index_mod
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.merge.index import AncestorOursTheirs, ConflictEntry

    monkeypatch.setattr(index_mod, "_BINARY_THRESHOLD", 3)
    repo = KartRepo.init_repository(tmp_path / "r")
    conflicts = {}
    for i in range(5):
        entry = lambda v: ConflictEntry(f"ds/.table-dataset/feature/aa/k{i}", f"{v:040x}")
        conflicts[f"ds:feature:{i}"] = AncestorOursTheirs(
            entry(i), entry(i + 1), None if i == 2 else entry(i + 2)
        )
    mi = MergeIndex("c" * 40, conflicts)
    mi.add_resolve("ds:feature:1", [ConflictEntry("p", "d" * 40)])
    mi.write_to_repo(repo)

    raw = open(repo.gitdir_file("MERGE_INDEX"), "rb").read()
    assert raw.startswith(b"KMIX2\n")

    mi2 = MergeIndex.read_from_repo(repo)
    assert mi2.merged_tree == mi.merged_tree
    assert sorted(mi2.conflicts) == sorted(mi.conflicts)
    assert mi2.conflicts["ds:feature:2"].theirs is None
    got = mi2.conflicts["ds:feature:4"]
    assert got.ours.path == conflicts["ds:feature:4"].ours.path
    assert got.ours.oid == conflicts["ds:feature:4"].ours.oid
    assert mi2.resolves["ds:feature:1"][0].oid == "d" * 40

    # below the threshold stays JSON
    monkeypatch.setattr(index_mod, "_BINARY_THRESHOLD", 1000)
    mi.write_to_repo(repo)
    raw = open(repo.gitdir_file("MERGE_INDEX"), "rb").read()
    assert raw.lstrip().startswith(b"{")
    mi3 = MergeIndex.read_from_repo(repo)
    assert sorted(mi3.conflicts) == sorted(mi.conflicts)


def test_merge_index_kmix1_backcompat():
    """A KMIX1 file (pre-dedup format: every version carries its own full
    path block) still reads — merges left in progress across an upgrade
    must survive."""
    import json as _json
    import struct as _struct

    import numpy as np

    from kart_tpu.merge.index import MergeIndex

    header = _json.dumps(
        {"mergedTree": "b" * 40, "n": 2, "resolves": {}}
    ).encode()
    labels = b"ds:feature:0\x00ds:feature:1"
    paths = b"ds/.table-dataset/feature/aa/k0\x00ds/.table-dataset/feature/aa/k1"
    blocks = [labels]
    for v in range(3):
        present = bytes([1, 1])
        oids = np.full((2, 20), v + 1, dtype=np.uint8).tobytes()
        blocks += [present, oids, paths]
    raw = b"KMIX1\n" + _struct.pack("<I", len(header)) + header
    for b in blocks:
        raw += _struct.pack("<Q", len(b)) + b
    mi = MergeIndex._from_binary(raw)
    assert sorted(mi.conflicts) == ["ds:feature:0", "ds:feature:1"]
    aot = mi.conflicts["ds:feature:1"]
    assert aot.ancestor.oid == "01" * 20
    assert aot.theirs.oid == "03" * 20
    assert aot.ours.path == "ds/.table-dataset/feature/aa/k1"


def test_columnar_conflicts_mapping_and_binary():
    """materialise_conflicts returns a columnar mapping whose entries,
    iteration order and parsed KMIX2 form match the equivalent plain-dict index —
    including rows absent from some versions (delete/edit conflicts)."""
    import numpy as np

    from kart_tpu.merge import materialise_conflicts
    from kart_tpu.merge.index import (
        AncestorOursTheirs,
        ColumnarConflicts,
        ConflictEntry,
    )
    from kart_tpu.models.paths import PathEncoder
    from kart_tpu.ops.blocks import FeatureBlock

    encoder = PathEncoder.INT_PK_ENCODER

    def block(keys_oids):
        keys = np.array(sorted(keys_oids), dtype=np.int64)
        oids = np.zeros((len(keys), 5), dtype=np.uint32)
        for i, k in enumerate(keys):
            oids[i, 0] = keys_oids[k]
        paths = [encoder.encode_pks_to_path((int(k),)) for k in keys]
        return FeatureBlock.from_arrays(keys, oids, paths)

    # pk 1: edit/edit conflict; pk 2: delete(ours)/edit(theirs);
    # pk 3: edit(ours)/delete(theirs)
    a = block({1: 10, 2: 20, 3: 30})
    o = block({1: 11, 3: 31})
    t = block({1: 12, 2: 22})

    class _Ds:
        path_encoder = encoder

    union = np.array([1, 2, 3], dtype=np.int64)
    conflict_idx = np.arange(3)
    cc = materialise_conflicts(
        "ds", [a, o, t], [_Ds(), _Ds(), _Ds()], "inner", union, conflict_idx
    )
    assert isinstance(cc, ColumnarConflicts)
    assert len(cc) == 3
    assert list(cc) == ["ds:feature:1", "ds:feature:2", "ds:feature:3"]
    assert "ds:feature:2" in cc and "ds:feature:99" not in cc

    aot = cc["ds:feature:2"]
    assert aot.ours is None  # deleted in ours
    assert aot.ancestor.oid.startswith("14")  # 20 -> 0x14 first byte LE word
    assert aot.theirs.path == "inner/feature/" + encoder.encode_pks_to_path((2,))

    # a plain-dict build of the same conflicts parses back identically
    # (byte streams may differ: columnar int-pk columns serialise as KMIX2
    # derived blocks, dict columns as joined path strings)
    dict_conflicts = {label: aot for label, aot in cc.items()}
    raw_columnar = MergeIndex("a" * 40, cc)._to_binary()
    raw_dict = MergeIndex("a" * 40, dict_conflicts)._to_binary()
    parsed_c = MergeIndex._from_binary(raw_columnar)
    parsed_d = MergeIndex._from_binary(raw_dict)
    assert list(parsed_c.conflicts) == list(parsed_d.conflicts)
    for label in parsed_c.conflicts:
        c_aot, d_aot = parsed_c.conflicts[label], parsed_d.conflicts[label]
        for name in ("ancestor", "ours", "theirs"):
            ce, de = c_aot.get(name), d_aot.get(name)
            assert (ce is None) == (de is None), (label, name)
            if ce is not None:
                assert ce.path == de.path and ce.oid == de.oid, (label, name)

    mi2 = MergeIndex._from_binary(raw_columnar)
    assert isinstance(mi2.conflicts, ColumnarConflicts)
    assert list(mi2.conflicts) == list(cc)
    got = mi2.conflicts["ds:feature:3"]
    assert got.theirs is None and got.ours.oid == cc["ds:feature:3"].ours.oid
    # rewrite of a read-back index is byte-identical (resolve flow)
    assert MergeIndex("a" * 40, mi2.conflicts)._to_binary() == raw_columnar


def test_encode_paths_joined_bytes_matches_batch():
    import numpy as np

    from kart_tpu.models.paths import PathEncoder

    enc = PathEncoder.INT_PK_ENCODER
    pks = np.array([0, 1, 127, 128, 255, 65535, 2**31, 2**63 - 1, -1, -129], dtype=np.int64)
    joined = enc.encode_paths_joined_bytes(pks, prefix=b"pre/", sep=b"\x00")
    expected = "\x00".join("pre/" + p for p in enc.encode_paths_batch(pks)).encode()
    assert joined == expected
    assert enc.encode_paths_joined_bytes(np.zeros(0, dtype=np.int64)) == b""


class TestStreamedMergeClassify:
    def test_matches_monolithic(self, monkeypatch):
        """Chunked double-buffered merge classify must reproduce
        merge_classify exactly for every chunk size (boundaries never split
        a key's 3-way decision)."""
        import numpy as np

        from kart_tpu.ops.merge_kernel import (
            merge_classify,
            merge_classify_streamed,
        )
        from kart_tpu.parallel.sharded_diff import synthetic_block

        monkeypatch.setenv("KART_DIFF_SHARDED", "0")
        n = 4000
        anc = synthetic_block(n, seed=11)
        ours = synthetic_block(n, seed=11)
        ours.oids = ours.oids.copy()
        theirs = synthetic_block(n, seed=11)
        theirs.oids = theirs.oids.copy()
        rng = np.random.default_rng(12)
        both = rng.choice(n, size=300, replace=False)
        ours.oids[both, 0] ^= 1
        theirs.oids[both, 0] ^= 2
        ours.oids[rng.choice(n, 200, replace=False), 1] ^= 3
        theirs.oids[rng.choice(n, 250, replace=False), 2] ^= 4

        want = merge_classify(anc, ours, theirs)
        for chunk_rows in (257, 1024, 10_000):
            got = merge_classify_streamed(
                anc, ours, theirs, chunk_rows=chunk_rows
            )
            for a, b in zip(got[:3], want[:3]):
                np.testing.assert_array_equal(a, b)
            assert got[3] == want[3]
            assert got[3]["conflicts"] >= 300

    def test_disjoint_sides(self, monkeypatch):
        """Renumbered shape: ours adds a whole new key range."""
        import numpy as np

        from kart_tpu.ops.blocks import FeatureBlock
        from kart_tpu.ops.merge_kernel import (
            merge_classify,
            merge_classify_streamed,
        )

        monkeypatch.setenv("KART_DIFF_SHARDED", "0")

        def block(lo, hi):
            keys = np.arange(lo, hi, dtype=np.int64)
            oids = np.ones((len(keys), 5), dtype=np.uint32)
            return FeatureBlock.from_arrays(keys, oids, [str(k) for k in keys])

        anc = block(0, 2000)
        ours = block(1000, 4000)  # dropped 0..999, added 2000..3999
        theirs = block(0, 2000)
        want = merge_classify(anc, ours, theirs)
        got = merge_classify_streamed(anc, ours, theirs, chunk_rows=333)
        for a, b in zip(got[:3], want[:3]):
            np.testing.assert_array_equal(a, b)
        assert got[3] == want[3]
