"""Shapefile import: hand-built .shp/.dbf/.prj fixtures (the format is a
fixed binary layout, so the fixtures are written byte-by-byte — the same
known-answer approach the reference uses with archived repos)."""

import struct

import pytest

from kart_tpu.importer import ImportSource, ImportSourceError
from kart_tpu.importer.shapefile import (
    DbfReader,
    ShapefileImportSource,
    ShpReader,
)

WGS84_WKT = (
    'GEOGCS["WGS 84",DATUM["WGS_1984",SPHEROID["WGS 84",6378137,298.257]],'
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433,'
    'AUTHORITY["EPSG","9122"]],AUTHORITY["EPSG","4326"]]'
)


def _shp_header(shape_type, content_length_words):
    h = struct.pack(">i", 9994) + b"\x00" * 20
    h += struct.pack(">i", 50 + content_length_words)
    h += struct.pack("<2i", 1000, shape_type)
    h += struct.pack("<8d", 0, 0, 10, 10, 0, 0, 0, 0)
    return h


def write_point_shp(path, points):
    """points: [(x, y)] -> minimal Point shapefile."""
    records = b""
    for i, (x, y) in enumerate(points, start=1):
        content = struct.pack("<i", 1) + struct.pack("<2d", x, y)
        records += struct.pack(">2i", i, len(content) // 2) + content
    with open(path, "wb") as f:
        f.write(_shp_header(1, len(records) // 2) + records)


def write_polygon_shp(path, polygons):
    """polygons: [[ring, ...]] (ring = [(x, y), ...]) -> Polygon shapefile."""
    records = b""
    for i, rings in enumerate(polygons, start=1):
        npoints = sum(len(r) for r in rings)
        content = struct.pack("<i", 5)
        content += struct.pack("<4d", 0, 0, 10, 10)
        content += struct.pack("<2i", len(rings), npoints)
        start = 0
        for r in rings:
            content += struct.pack("<i", start)
            start += len(r)
        for r in rings:
            for x, y in r:
                content += struct.pack("<2d", x, y)
        records += struct.pack(">2i", i, len(content) // 2) + content
    with open(path, "wb") as f:
        f.write(_shp_header(5, len(records) // 2) + records)


def write_dbf(path, fields, rows):
    """fields: [(name, type_char, length, decimals)]; rows: [dict]."""
    record_size = 1 + sum(f[2] for f in fields)
    header_size = 32 + 32 * len(fields) + 1
    head = struct.pack(
        "<B3Bihh", 3, 24, 1, 1, len(rows), header_size, record_size
    )
    head += b"\x00" * 20
    for name, type_char, length, decimals in fields:
        desc = name.encode()[:11].ljust(11, b"\x00")
        desc += type_char.encode()
        desc += b"\x00" * 4
        desc += bytes([length, decimals])
        desc += b"\x00" * 14
        head += desc
    head += b"\x0d"
    body = b""
    for row in rows:
        rec = b" "
        for name, type_char, length, decimals in fields:
            v = row.get(name)
            if v is None:
                cell = b" " * length
            elif type_char == "C":
                cell = str(v).encode()[:length].ljust(length)
            elif type_char in ("N", "F"):
                cell = str(v).encode()[:length].rjust(length)
            elif type_char == "L":
                cell = (b"T" if v else b"F").ljust(length)
            elif type_char == "D":
                cell = v.replace("-", "").encode().ljust(length)
            else:
                cell = str(v).encode().ljust(length)[:length]
            rec += cell
        body += rec
    with open(path, "wb") as f:
        f.write(head + body + b"\x1a")


@pytest.fixture
def points_shapefile(tmp_path):
    base = tmp_path / "cities"
    write_point_shp(base.with_suffix(".shp"), [(1.0, 2.0), (3.5, -4.5), (7, 8)])
    write_dbf(
        base.with_suffix(".dbf"),
        [("name", "C", 20, 0), ("pop", "N", 10, 0), ("area", "F", 12, 0),
         ("capital", "L", 1, 0), ("founded", "D", 8, 0)],
        [
            {"name": "alpha", "pop": 1000, "area": 1.5, "capital": True,
             "founded": "1900-01-02"},
            {"name": "beta", "pop": 2000, "area": 2.5, "capital": False,
             "founded": "1950-06-30"},
            {"name": "gamma", "pop": None, "area": None, "capital": None,
             "founded": None},
        ],
    )
    base.with_suffix(".prj").write_text(WGS84_WKT)
    return base.with_suffix(".shp")


class TestShpReader:
    def test_points(self, points_shapefile):
        shapes = list(ShpReader(str(points_shapefile)))
        assert [rec_no for rec_no, _ in shapes] == [1, 2, 3]
        assert shapes[0][1][3] == (1.0, 2.0)

    def test_polygon_with_hole(self, tmp_path):
        path = tmp_path / "poly.shp"
        outer = [(0, 0), (0, 10), (10, 10), (10, 0), (0, 0)]  # CW
        hole = [(2, 2), (4, 2), (4, 4), (2, 4), (2, 2)]  # CCW
        write_polygon_shp(path, [[outer, hole]])
        ((rec_no, value),) = list(ShpReader(str(path)))
        assert value[0] == "MultiPolygon"
        (poly,) = value[3]
        assert len(poly[3]) == 2  # outer + 1 hole
        assert poly[3][0][0] == (0.0, 0.0)
        assert poly[3][1][0] == (2.0, 2.0)

    def test_two_outer_rings_make_two_polygons(self, tmp_path):
        path = tmp_path / "multi.shp"
        ring_a = [(0, 0), (0, 2), (2, 2), (2, 0), (0, 0)]  # CW
        ring_b = [(5, 5), (5, 7), (7, 7), (7, 5), (5, 5)]  # CW
        write_polygon_shp(path, [[ring_a, ring_b]])
        ((_, value),) = list(ShpReader(str(path)))
        assert len(value[3]) == 2

    def test_not_a_shapefile(self, tmp_path):
        bad = tmp_path / "bad.shp"
        bad.write_bytes(b"\x00" * 200)
        with pytest.raises(ImportSourceError, match="bad magic"):
            ShpReader(str(bad))


class TestDbfReader:
    def test_types_and_nulls(self, points_shapefile):
        dbf = DbfReader(str(points_shapefile.with_suffix(".dbf")))
        assert [f[0] for f in dbf.fields] == [
            "name", "pop", "area", "capital", "founded",
        ]
        rows = list(dbf.records())
        assert rows[0]["name"] == "alpha"
        assert rows[0]["pop"] == 1000
        assert rows[0]["area"] == 1.5
        assert rows[0]["capital"] is True
        assert rows[0]["founded"] == "1900-01-02"
        assert rows[2]["pop"] is None
        assert rows[2]["capital"] is None

    def test_v2_columns(self, points_shapefile):
        dbf = DbfReader(str(points_shapefile.with_suffix(".dbf")))
        cols = dict((n, (t, e)) for n, t, e in dbf.v2_columns())
        assert cols["name"] == ("text", {"length": 20})
        assert cols["pop"] == ("integer", {"size": 64})
        assert cols["capital"] == ("boolean", {})
        assert cols["founded"] == ("date", {})


class TestShapefileImportSource:
    def test_schema_and_features(self, points_shapefile):
        src = ShapefileImportSource(str(points_shapefile))
        schema = src.schema
        assert schema.pk_columns[0].name == "FID"
        geom_col = schema.first_geometry_column
        assert geom_col.name == "geom"
        assert geom_col.extra_type_info["geometryType"] == "POINT"
        assert geom_col.extra_type_info["geometryCRS"] == "EPSG:4326"
        assert src.crs_definitions() == {"EPSG:4326": WGS84_WKT}
        assert src.feature_count == 3
        features = list(src.features())
        assert features[0]["FID"] == 1
        assert features[0]["name"] == "alpha"
        env = features[1]["geom"].envelope()
        assert (env[0], env[2]) == (3.5, -4.5)

    def test_open_dispatch(self, points_shapefile):
        (src,) = ImportSource.open(str(points_shapefile))
        assert isinstance(src, ShapefileImportSource)
        assert src.dest_path == "cities"

    def test_full_import_roundtrip(self, points_shapefile, tmp_path):
        from kart_tpu.core.repo import KartRepo
        from kart_tpu.importer.importer import import_sources

        repo = KartRepo.init_repository(tmp_path / "repo")
        repo.config.set_many({"user.name": "T", "user.email": "t@x"})
        import_sources(
            repo, [ShapefileImportSource(str(points_shapefile))],
            message="import shp",
        )
        ds = repo.datasets("HEAD")["cities"]
        assert ds.feature_count == 3
        f = ds.get_feature([2])
        assert f["name"] == "beta"
        assert f["pop"] == 2000
        assert f["geom"] is not None


def test_postgres_import_gated():
    import importlib.util

    from kart_tpu.core.repo import NotFound
    from kart_tpu.importer.postgres import PostgresImportSource

    conn, db_schema, table = PostgresImportSource.parse_spec(
        "postgresql://host:5433/db/myschema/mytable"
    )
    assert conn[0] == "host" and conn[1] == 5433 and conn[2] == "db"
    assert (db_schema, table) == ("myschema", "mytable")
    if importlib.util.find_spec("psycopg2") is not None:
        pytest.skip("psycopg2 installed: the gate doesn't engage")
    with pytest.raises(NotFound, match="psycopg2"):
        PostgresImportSource.open_all("postgresql://host/db")


def test_deleted_dbf_rows_tombstone_features(tmp_path):
    """A '*'-flagged DBF row drops that feature but keeps later rows aligned
    with their shapes."""
    base = tmp_path / "del"
    write_point_shp(base.with_suffix(".shp"), [(1, 1), (2, 2), (3, 3)])
    write_dbf(
        base.with_suffix(".dbf"),
        [("name", "C", 10, 0)],
        [{"name": "one"}, {"name": "two"}, {"name": "three"}],
    )
    # flag record 2 deleted: records start after header; each is 11 bytes
    data = bytearray(base.with_suffix(".dbf").read_bytes())
    header_size = struct.unpack("<h", data[8:10])[0]
    record_size = struct.unpack("<h", data[10:12])[0]
    data[header_size + record_size] = ord("*")
    base.with_suffix(".dbf").write_bytes(bytes(data))

    src = ShapefileImportSource(str(base.with_suffix(".shp")))
    features = list(src.features())
    assert src.feature_count == 2
    assert [(f["FID"], f["name"]) for f in features] == [
        (1, "one"),
        (3, "three"),
    ]


def test_postgis_raw_ewkb_value_roundtrip():
    """ST_AsEWKB returns raw EWKB bytes; value_to_v2 must parse them."""
    from kart_tpu.adapters.postgis import PostgisAdapter
    from kart_tpu.geometry import Geometry
    from kart_tpu.models.schema import ColumnSchema

    g = Geometry.from_wkt("POINT(174.5 -41.3)", crs_id=4326)
    gcol = ColumnSchema(ColumnSchema.new_id(), "geom", "geometry", None, {})
    raw_ewkb = g.with_crs_id(4326).to_ewkb()
    assert PostgisAdapter.value_to_v2(memoryview(raw_ewkb), gcol) == \
        PostgisAdapter.value_to_v2(raw_ewkb.hex().upper(), gcol)
