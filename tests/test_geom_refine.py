"""Exact-refine property tests (ISSUE 20).

Three contracts:

1. **Brute-force equivalence** — the vectorized host refine
   (:func:`kart_tpu.geom.refine_pairs_host`) agrees with an independent
   scalar pure-Python implementation of the same semantics (inclusive
   segment contact, even-odd containment with the half-open vertex rule)
   on an edge-case matrix: polar rings, near-anti-meridian spans,
   touching corners, collinear overlap, point-on-edge, holes, NaN/empty
   extraction fallbacks, plus a randomized all-pairs sweep.
2. **Host/sharded bit-identity** — the 8-device virtual-mesh kernel
   returns the identical verdict array (predicates are operator-only
   shared source, so this is by construction — the test guards the
   padding/batching plumbing around them).
3. **Monotonicity** — exact verdicts only ever *drop* envelope-stage
   candidates (exact ⊆ bbox), end-to-end through the scan.
"""

import numpy as np
import pytest

from kart_tpu.geom import (
    COORD_SCALE,
    KIND_NONE,
    KIND_POLY,
    VertexColumn,
    bbox_vertex_column,
    refine_pairs_host,
    vertex_column_from_blobs,
)
from kart_tpu.geometry import Geometry


def _col_from_wkt(wkts):
    """WKT list (or None) -> VertexColumn via the real GPKG blob path."""
    blobs = [
        bytes(Geometry.from_wkt(w)) if w is not None else None for w in wkts
    ]
    return vertex_column_from_blobs(blobs)


# ---------------------------------------------------------------------------
# the independent scalar reference
# ---------------------------------------------------------------------------


def _orient(ax, ay, bx, by, cx, cy):
    return (bx - ax) * (cy - ay) - (by - ay) * (cx - ax)


def _seg_contact(a, b):
    ax0, ay0, ax1, ay1 = a
    bx0, by0, bx1, by1 = b
    d1 = _orient(bx0, by0, bx1, by1, ax0, ay0)
    d2 = _orient(bx0, by0, bx1, by1, ax1, ay1)
    d3 = _orient(ax0, ay0, ax1, ay1, bx0, by0)
    d4 = _orient(ax0, ay0, ax1, ay1, bx1, by1)
    if ((d1 > 0 and d2 < 0) or (d1 < 0 and d2 > 0)) and (
        (d3 > 0 and d4 < 0) or (d3 < 0 and d4 > 0)
    ):
        return True

    def on(px, py, sx0, sy0, sx1, sy1):
        return (
            _orient(sx0, sy0, sx1, sy1, px, py) == 0
            and min(sx0, sx1) <= px <= max(sx0, sx1)
            and min(sy0, sy1) <= py <= max(sy0, sy1)
        )

    return (
        on(ax0, ay0, bx0, by0, bx1, by1)
        or on(ax1, ay1, bx0, by0, bx1, by1)
        or on(bx0, by0, ax0, ay0, ax1, ay1)
        or on(bx1, by1, ax0, ay0, ax1, ay1)
    )


def _point_in(px, py, segs):
    """Even-odd with the half-open upward rule, exact integer math."""
    inside = False
    for sx0, sy0, sx1, sy1 in segs:
        if (sy0 <= py) != (sy1 <= py):
            cr = (sx1 - sx0) * (py - sy0) - (sy1 - sy0) * (px - sx0)
            if (sy1 > sy0 and cr > 0) or (sy1 < sy0 and cr < 0):
                inside = not inside
    return inside


def _scalar_segs(col, i):
    x0, y0, x1, y1 = col.segments(i)
    return list(
        zip(
            (int(v) for v in x0),
            (int(v) for v in y0),
            (int(v) for v in x1),
            (int(v) for v in y1),
        )
    )


def _brute_pair(col_a, i, col_b, j):
    sa = _scalar_segs(col_a, i)
    sb = _scalar_segs(col_b, j)
    if not sa or not sb:
        return False
    for a in sa:
        for b in sb:
            if _seg_contact(a, b):
                return True
    if col_b.kinds[j] == KIND_POLY and any(
        _point_in(a[0], a[1], sb) for a in sa
    ):
        return True
    if col_a.kinds[i] == KIND_POLY and any(
        _point_in(b[0], b[1], sa) for b in sb
    ):
        return True
    return False


def _all_pairs(col_a, col_b):
    ia, ib = np.meshgrid(
        np.arange(len(col_a)), np.arange(len(col_b)), indexing="ij"
    )
    return ia.ravel().astype(np.int64), ib.ravel().astype(np.int64)


# ---------------------------------------------------------------------------
# 1. brute-force equivalence
# ---------------------------------------------------------------------------

#: the edge-case matrix: deliberate touching/collinear/degenerate shapes,
#: polar latitudes, and spans hugging (not crossing) the anti-meridian
EDGE_WKTS_A = [
    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))",
    # hole: a point inside the hole must NOT intersect
    "POLYGON ((20 20, 40 20, 40 40, 20 40, 20 20),"
    " (25 25, 35 25, 35 35, 25 35, 25 25))",
    "LINESTRING (0 0, 10 10)",
    "POINT (5 5)",
    "POINT (10 0)",  # exactly on a corner of the first polygon
    "MULTIPOINT (1 1, 9 9)",
    "LINESTRING (-179.99 70, -179.5 75)",  # near the anti-meridian
    "POLYGON ((-180 85, 180 85, 180 90, -180 90, -180 85))",  # polar cap
    "LINESTRING (0 5, 0 5)",  # degenerate zero-length line
    None,  # extraction failure -> kind 0
]
EDGE_WKTS_B = [
    "POLYGON ((5 5, 15 5, 15 15, 5 15, 5 5))",  # overlaps A0
    "POINT (30 30)",  # inside A1's hole
    "POINT (26 21)",  # inside A1's shell, outside its hole
    "LINESTRING (10 0, 20 -10)",  # touches A0 at its corner only
    "LINESTRING (2 2, 8 8)",  # collinear sub-segment of A2
    "POLYGON ((100 -90, 101 -90, 101 -89, 100 -89, 100 -90))",  # south pole
    "POLYGON ((-180 60, -179 60, -179 80, -180 80, -180 60))",
    "POINT (0 90)",  # the north pole itself
    "MULTILINESTRING ((50 50, 60 60), (0 5, 1 5))",
    "POLYGON EMPTY",  # empty -> kind 0
]


def test_refine_matches_bruteforce_on_edge_matrix():
    col_a = _col_from_wkt(EDGE_WKTS_A)
    col_b = _col_from_wkt(EDGE_WKTS_B)
    assert col_a.kinds[-1] == KIND_NONE and col_b.kinds[-1] == KIND_NONE
    ia, ib = _all_pairs(col_a, col_b)
    got = refine_pairs_host(col_a, ia, col_b, ib)
    want = np.asarray(
        [_brute_pair(col_a, int(i), col_b, int(j)) for i, j in zip(ia, ib)]
    )
    assert np.array_equal(got, want)
    # spot-check the semantics the matrix encodes
    verdict = {(int(i), int(j)): bool(v) for i, j, v in zip(ia, ib, got)}
    assert verdict[(0, 0)] is True  # overlapping boxes
    assert verdict[(1, 1)] is False  # point inside the hole
    assert verdict[(1, 2)] is True  # point in shell, outside hole
    assert verdict[(0, 3)] is True  # corner touch counts (inclusive)
    assert verdict[(2, 4)] is True  # collinear overlap counts
    assert verdict[(9, 0)] is False  # kind-0 row never intersects


def test_refine_matches_bruteforce_randomized():
    rng = np.random.default_rng(2020)

    def wkt_box(cx, cy, w, h):
        x0, y0, x1, y1 = cx - w, cy - h, cx + w, cy + h
        return (
            f"POLYGON (({x0} {y0}, {x1} {y0}, {x1} {y1}, "
            f"{x0} {y1}, {x0} {y0}))"
        )

    wkts_a, wkts_b = [], []
    for out in (wkts_a, wkts_b):
        for _ in range(12):
            cx, cy = rng.uniform(-5, 5, 2)
            shape = rng.integers(0, 3)
            if shape == 0:
                out.append(wkt_box(cx, cy, *rng.uniform(0.5, 4, 2)))
            elif shape == 1:
                dx, dy = rng.uniform(-4, 4, 2)
                out.append(
                    f"LINESTRING ({cx} {cy}, {cx + dx} {cy + dy})"
                )
            else:
                out.append(f"POINT ({cx} {cy})")
    col_a = _col_from_wkt(wkts_a)
    col_b = _col_from_wkt(wkts_b)
    ia, ib = _all_pairs(col_a, col_b)
    got = refine_pairs_host(col_a, ia, col_b, ib)
    want = np.asarray(
        [_brute_pair(col_a, int(i), col_b, int(j)) for i, j in zip(ia, ib)]
    )
    assert np.array_equal(got, want)
    assert got.any() and not got.all()  # the sweep exercises both verdicts


# ---------------------------------------------------------------------------
# 2. host/sharded bit-identity on the virtual mesh
# ---------------------------------------------------------------------------


def test_sharded_refine_bit_identical_to_host(monkeypatch):
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    from kart_tpu.diff.backend import refine_intersects, sharded_refine_pairs

    col_a = _col_from_wkt(EDGE_WKTS_A)
    col_b = _col_from_wkt(EDGE_WKTS_B)
    rng = np.random.default_rng(7)
    ia = rng.integers(0, len(col_a), 500).astype(np.int64)
    ib = rng.integers(0, len(col_b), 500).astype(np.int64)
    host = refine_pairs_host(col_a, ia, col_b, ib)

    monkeypatch.setenv("KART_GEOM_BATCH_ROWS", "64")  # force multi-batch
    sharded = sharded_refine_pairs(col_a, ia, col_b, ib)
    assert sharded.dtype == bool and np.array_equal(sharded, host)

    # and through the routing seam, forced onto the mesh
    monkeypatch.setenv("KART_DIFF_SHARDED", "1")
    routed = refine_intersects(col_a, ia, col_b, ib)
    assert np.array_equal(routed, host)


# ---------------------------------------------------------------------------
# 3. monotonicity: exact ⊆ bbox, end-to-end through the scan stage
# ---------------------------------------------------------------------------


def test_scan_refine_only_drops_candidates(monkeypatch):
    """A diagonal line whose envelope clips the query rectangle but whose
    geometry misses it is dropped by refine and kept by --approx; every
    exact survivor is an envelope-stage candidate."""
    from kart_tpu.query.scan import _refine_bbox_indices

    # envelope of each diagonal is the unit box around it
    diags = [
        "LINESTRING (0 0, 10 10)",  # envelope hits (0,8)-(2,10); line misses
        "LINESTRING (0 10, 10 0)",  # passes through the corner box
        "POINT (1 9)",  # inside the box
    ]
    col = _col_from_wkt(diags)
    env = np.asarray(
        [[0, 0, 10, 10], [0, 0, 10, 10], [1, 9, 1, 9]], dtype=np.float32
    )

    class _Block:
        envelopes = env

        def vertex_column(self):
            return col

    block = _Block()

    class _DS:
        pass

    idx = np.arange(3, dtype=np.int64)
    stats = {"pairs_refined": 0, "refine_dropped": 0}
    kept = _refine_bbox_indices(
        _DS(), block, idx, (0.0, 8.0, 2.0, 10.0), None, stats
    )
    assert set(kept.tolist()) <= set(idx.tolist())  # monotone: only drops
    assert kept.tolist() == [1, 2]  # diagonal 0's bbox hit is refined away
    assert stats["pairs_refined"] == 3 and stats["refine_dropped"] == 1

    # the query rectangle itself round-trips through the box builder
    qcol = bbox_vertex_column((0.0, 8.0, 2.0, 10.0))
    assert qcol is not None and qcol.kinds[0] == KIND_POLY
    assert bbox_vertex_column((170.0, 0.0, -170.0, 10.0)) is None  # wrap


def test_exact_counts_never_exceed_approx(tmp_path):
    """End-to-end monotonicity on a real repo: for a grid of query
    rectangles, the exact scan count never exceeds the approx count
    (and on box-geometry synth data they are equal)."""
    from kart_tpu.query import run_query
    from kart_tpu.synth import synth_repo

    repo, info = synth_repo(str(tmp_path / "m"), 1500, spatial=True, seed=11)
    base = info["base_commit"]
    for bbox in ("0,0,30,30", "-10,-10,0.5,0.5", "100,-50,120,-30"):
        exact = run_query(repo, base, "synth", bbox=bbox)
        approx = run_query(repo, base, "synth", bbox=bbox, approx=True)
        assert exact["exact"] is True and approx["exact"] is False
        assert exact["count"] <= approx["count"]
        assert exact["count"] == approx["count"]  # geometry IS the envelope
