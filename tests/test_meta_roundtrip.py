"""Every meta item must roundtrip through a fresh GPKG working-copy checkout
with zero diff (VERDICT r1 weak #4: a title equal to the table name was
dropped on read-back and showed as 'meta: 1 deletes' right after import).
Reference: kart/working_copy/base.py:520-632 meta alignment."""

import sqlite3

import pytest
from click.testing import CliRunner

from kart_tpu.cli import cli

from helpers import create_points_gpkg


def _variant_gpkg(tmp_path, name, *, identifier, description, srs_id=4326):
    path = str(tmp_path / f"{name}.gpkg")
    create_points_gpkg(path, n=5, srs_id=srs_id)
    con = sqlite3.connect(path)
    con.execute(
        "UPDATE gpkg_contents SET identifier = ?, description = ?",
        (identifier, description),
    )
    con.commit()
    con.close()
    return path


@pytest.mark.parametrize(
    "identifier,description",
    [
        ("points", None),  # title == table name (the r1 bug)
        ("A custom title", None),
        (None, None),
        ("", ""),
        ("points", "with a description"),
        ("Custom", "and a description"),
    ],
    ids=["title-eq-table", "custom-title", "no-title", "empty", "desc", "both"],
)
def test_import_then_status_clean(tmp_path, monkeypatch, identifier, description):
    gpkg = _variant_gpkg(
        tmp_path, "src", identifier=identifier, description=description
    )
    runner = CliRunner()
    repo_dir = str(tmp_path / "repo")
    assert runner.invoke(cli, ["init", repo_dir]).exit_code == 0
    monkeypatch.chdir(repo_dir)
    r = runner.invoke(cli, ["import", gpkg])
    assert r.exit_code == 0, r.output

    r = runner.invoke(cli, ["status"])
    assert r.exit_code == 0, r.output
    assert "working copy clean" in r.output, r.output

    r = runner.invoke(cli, ["diff", "-o", "json"])
    assert r.exit_code == 0, r.output
    assert '"kart.diff/v1+hexwkb": {}' in r.output, r.output


def test_import_then_status_clean_custom_crs(tmp_path, monkeypatch):
    gpkg = _variant_gpkg(
        tmp_path, "src", identifier="NZ layer", description=None, srs_id=2193
    )
    runner = CliRunner()
    repo_dir = str(tmp_path / "repo")
    assert runner.invoke(cli, ["init", repo_dir]).exit_code == 0
    monkeypatch.chdir(repo_dir)
    r = runner.invoke(cli, ["import", gpkg])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["status"])
    assert "working copy clean" in r.output, r.output


def test_commit_preserves_title_on_feature_edit(tmp_path, monkeypatch):
    """A feature-only commit must not silently drop the dataset title
    (the r1 bug committed the phantom meta delete)."""
    gpkg = _variant_gpkg(tmp_path, "src", identifier="points", description=None)
    runner = CliRunner()
    repo_dir = str(tmp_path / "repo")
    assert runner.invoke(cli, ["init", repo_dir]).exit_code == 0
    monkeypatch.chdir(repo_dir)
    assert runner.invoke(cli, ["import", gpkg]).exit_code == 0

    import glob

    from helpers import wc_connect

    wc = glob.glob(f"{repo_dir}/*.gpkg")[0]
    con = wc_connect(wc)
    con.execute("UPDATE points SET name = 'edited' WHERE fid = 2")
    con.commit()
    con.close()

    r = runner.invoke(cli, ["commit", "-m", "edit"])
    assert r.exit_code == 0, r.output

    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(repo_dir)
    ds = repo.structure("HEAD").datasets["points"]
    assert ds.get_meta_item("title") == "points"
