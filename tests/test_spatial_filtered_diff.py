"""Spatially-filtered diffs (reference: kart/base_diff_writer.py:279-341 —
on a spatially-filtered clone, `kart diff` streams only deltas whose old OR
new value matches the filter; BASELINE config #4 measures the same path at
100M via the envelope-column prefilter).

Layers under test:
* writer-level exact filtering (value residue) on a real small repo;
* engine-level envelope prefilter on sidecar blocks (synth spatial repo),
  including its parity with the writer-level count;
* envelope sidecar column roundtrip.
"""

import json

import numpy as np
import pytest

from helpers import edit_commit, make_imported_repo

# covers fids 1..5 (points sit at lon 100+i, lat -40-0.1i)
FILTER_W5 = "EPSG:4326;POLYGON((100 -42, 106 -42, 106 -39, 100 -39, 100 -42))"


def set_filter(repo, spec_text):
    from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

    spec = ResolvedSpatialFilterSpec.from_spec_string(spec_text)
    repo.config.set_many(spec.config_items())


def diff_json(repo, spec="HEAD^...HEAD"):
    from kart_tpu.diff.writers import JsonDiffWriter
    import io

    out = io.StringIO()
    writer = JsonDiffWriter(repo, spec, output_path=out, json_style="compact")
    writer.write_diff()
    return json.loads(out.getvalue())["kart.diff/v1+hexwkb"]


class TestWriterLevelFilter:
    def test_only_matching_deltas_stream(self, tmp_path):
        repo, ds_path = make_imported_repo(tmp_path, n=10)
        edit_commit(
            repo, ds_path,
            updates=[
                {**repo.datasets()[ds_path].get_feature([fid]), "name": "edited"}
                for fid in (2, 8)
            ],
            message="edit in+out",
        )
        # no filter: both updates
        assert len(diff_json(repo)[ds_path]["feature"]) == 2
        set_filter(repo, FILTER_W5)
        feats = diff_json(repo)[ds_path]["feature"]
        assert len(feats) == 1
        assert feats[0]["+"]["fid"] == 2

    def test_either_side_matches(self, tmp_path):
        """A feature moved from inside the filter to outside still shows
        (reference: matches_delta_values tests old OR new)."""
        repo, ds_path = make_imported_repo(tmp_path, n=10)
        ds = repo.datasets()[ds_path]
        from kart_tpu.geometry import Geometry

        moved = dict(ds.get_feature([3]))
        moved["geom"] = Geometry.from_wkt("POINT (150 -20)")  # outside
        edit_commit(repo, ds_path, updates=[moved], message="move out")
        set_filter(repo, FILTER_W5)
        feats = diff_json(repo)[ds_path]["feature"]
        assert len(feats) == 1
        assert feats[0]["-"]["fid"] == 3

    def test_insert_outside_filter_hidden(self, tmp_path):
        repo, ds_path = make_imported_repo(tmp_path, n=10)
        from kart_tpu.geometry import Geometry

        edit_commit(
            repo, ds_path,
            inserts=[
                {"fid": 100, "geom": Geometry.from_wkt("POINT (160 10)"),
                 "name": "far away", "rating": 1.0},
                {"fid": 101, "geom": Geometry.from_wkt("POINT (102.5 -40.0)"),
                 "name": "nearby", "rating": 1.0},
            ],
            message="inserts",
        )
        set_filter(repo, FILTER_W5)
        feats = diff_json(repo)[ds_path]["feature"]
        assert [f["+"]["fid"] for f in feats] == [101]

    def test_exit_code_agrees_with_output(self, tmp_path):
        """An all-out-of-filter diff must report has_changes=False — the
        exit code agrees with the (empty) output, across writers."""
        import io

        from kart_tpu.diff.writers import JsonDiffWriter, TextDiffWriter

        repo, ds_path = make_imported_repo(tmp_path, n=10)
        edit_commit(
            repo, ds_path,
            updates=[{**repo.datasets()[ds_path].get_feature([8]), "name": "x"}],
            message="out-of-filter edit",
        )
        set_filter(repo, FILTER_W5)
        for writer_cls in (TextDiffWriter, JsonDiffWriter):
            out = io.StringIO()
            writer = writer_cls(repo, "HEAD^...HEAD", output_path=out)
            assert writer.write_diff() is False, writer_cls.__name__

    def test_feature_count_respects_filter(self, tmp_path):
        from click.testing import CliRunner

        from kart_tpu.cli import cli

        repo, ds_path = make_imported_repo(tmp_path, n=10)
        edit_commit(
            repo, ds_path,
            updates=[
                {**repo.datasets()[ds_path].get_feature([fid]), "name": "e"}
                for fid in (2, 3, 8, 9)
            ],
            message="edits",
        )
        runner = CliRunner()
        args = ["-C", str(tmp_path / "repo"), "diff", "HEAD^...HEAD", "-o", "feature-count"]
        r = runner.invoke(cli, args)
        assert r.exit_code == 0 and "4 features changed" in r.output
        set_filter(repo, FILTER_W5)
        r = runner.invoke(cli, args)
        assert r.exit_code == 0 and "2 features changed" in r.output, r.output


class TestEnvelopePrefilter:
    @pytest.fixture(scope="class")
    def spatial_repo(self, tmp_path_factory):
        from kart_tpu.synth import synth_repo

        path = tmp_path_factory.mktemp("synthsp") / "repo"
        repo, info = synth_repo(str(path), 30_000, edit_frac=0.01, spatial=True)
        return repo, info

    def test_sidecar_envelopes_roundtrip(self, spatial_repo):
        from kart_tpu.diff import sidecar
        from kart_tpu.synth import synth_envelopes

        repo, info = spatial_repo
        ds = repo.structure("HEAD").datasets["synth"]
        block = sidecar.load_block(repo, ds)
        assert block is not None and block.envelopes is not None
        assert block.envelopes.shape == (block.count, 4)
        base = 1 << 24
        expect = synth_envelopes(np.asarray(block.keys[: block.count]))
        np.testing.assert_allclose(np.asarray(block.envelopes), expect)
        assert base == int(block.keys[0])

    def test_filtered_count_less_and_consistent(self, spatial_repo):
        from kart_tpu.diff.engine import get_dataset_feature_count_fast
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        repo, info = spatial_repo
        base_rs = repo.structure("HEAD^")
        target_rs = repo.structure("HEAD")
        unfiltered = get_dataset_feature_count_fast(base_rs, target_rs, "synth")
        assert unfiltered == info["n_edits"]

        spec = ResolvedSpatialFilterSpec.from_spec_string(
            "EPSG:4326;POLYGON((-180 -85, 0 -85, 0 85, -180 85, -180 -85))"
        )
        filtered = get_dataset_feature_count_fast(
            base_rs, target_rs, "synth", spatial_filter_spec=spec
        )
        assert 0 < filtered < unfiltered
        # ~half the globe -> roughly half the edits (quasi-uniform spread)
        assert abs(filtered - unfiltered / 2) < unfiltered * 0.2

    def test_prefilter_matches_envelope_recount(self, spatial_repo):
        """The engine prefilter count equals a direct recount: edits whose
        (old or new) envelope intersects the filter rect."""
        from kart_tpu.diff.engine import (
            get_dataset_feature_count_fast,
            spatial_prefilter_blocks,
        )
        from kart_tpu.diff import sidecar
        from kart_tpu.ops.bbox import bbox_intersects_np
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec
        from kart_tpu.synth import synth_envelopes

        repo, info = spatial_repo
        base_rs = repo.structure("HEAD^")
        target_rs = repo.structure("HEAD")
        rect = (20.0, -50.0, 140.0, 30.0)
        spec = ResolvedSpatialFilterSpec.from_spec_string(
            "EPSG:4326;POLYGON((20 -50, 140 -50, 140 30, 20 30, 20 -50))"
        )
        got = get_dataset_feature_count_fast(
            base_rs, target_rs, "synth", spatial_filter_spec=spec
        )
        # recount directly: synth edits are oid rewrites of known rows
        old_block = sidecar.load_block(repo, base_rs.datasets["synth"])
        new_block = sidecar.load_block(repo, target_rs.datasets["synth"])
        o = np.asarray(old_block.oids[: old_block.count])
        n = np.asarray(new_block.oids[: new_block.count])
        changed = (o != n).any(axis=1)
        envs = np.asarray(old_block.envelopes)
        hits = bbox_intersects_np(envs.astype(np.float64), np.asarray(rect))
        assert got == int((changed & hits).sum())

    def test_cli_feature_count_uses_prefilter(self, spatial_repo, tmp_path):
        from click.testing import CliRunner

        from kart_tpu.cli import cli
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        repo, info = spatial_repo
        spec = ResolvedSpatialFilterSpec.from_spec_string(
            "EPSG:4326;POLYGON((-180 -85, 0 -85, 0 85, -180 85, -180 -85))"
        )
        repo.config.set_many(spec.config_items())
        try:
            runner = CliRunner()
            r = runner.invoke(
                cli,
                ["-C", repo.workdir or repo.gitdir, "diff",
                 "HEAD^...HEAD", "-o", "feature-count"],
            )
            assert r.exit_code == 0, r.output
            import re as _re

            m = _re.search(r"(\d+) features changed", r.output)
            assert m, r.output
            count = int(m.group(1))
            assert 0 < count < info["n_edits"]
        finally:
            for key in spec.config_items():
                repo.del_config(key)


def test_quiet_writer_exit_code_with_filter(tmp_path):
    """-o quiet must answer for the FILTERED diff: in-filter change ->
    has_changes, out-of-filter-only change -> none."""
    import io

    from kart_tpu.diff.writers import QuietDiffWriter

    repo, ds_path = make_imported_repo(tmp_path, n=10)
    edit_commit(
        repo, ds_path,
        updates=[{**repo.datasets()[ds_path].get_feature([8]), "name": "x"}],
        message="out-of-filter",
    )
    set_filter(repo, FILTER_W5)
    w = QuietDiffWriter(repo, "HEAD^...HEAD", output_path=io.StringIO())
    assert w.write_diff() is False
    edit_commit(
        repo, ds_path,
        updates=[{**repo.datasets()[ds_path].get_feature([2]), "name": "y"}],
        message="in-filter",
    )
    w = QuietDiffWriter(repo, "HEAD^...HEAD", output_path=io.StringIO())
    assert w.write_diff() is True


def test_checkout_spatial_filter_rebuilds_wc(tmp_path):
    """`kart checkout --spatial-filter=...` sets the repo filter and
    rebuilds the working copy with exactly the in-filter features;
    'none' clears it and restores everything (reference: kart checkout
    --spatial-filter)."""
    import sqlite3

    from click.testing import CliRunner

    from kart_tpu.cli import cli

    repo, ds_path = make_imported_repo(tmp_path, n=10)
    args = ["-C", str(tmp_path / "repo")]
    runner = CliRunner()
    # create the WC first
    r = runner.invoke(cli, [*args, "checkout"])
    assert r.exit_code == 0, r.output
    wc_file = next(
        p for p in (tmp_path / "repo").iterdir() if p.suffix == ".gpkg"
    )

    def wc_fids():
        con = sqlite3.connect(wc_file)
        fids = sorted(r[0] for r in con.execute("SELECT fid FROM points"))
        con.close()
        return fids

    assert wc_fids() == list(range(1, 11))
    # 105.5 avoids fid 6 sitting exactly on the boundary (boundary matches)
    rect = "EPSG:4326;POLYGON((100 -42, 105.5 -42, 105.5 -39, 100 -39, 100 -42))"
    r = runner.invoke(cli, [*args, "checkout", "--spatial-filter", rect])
    assert r.exit_code == 0, r.output
    assert wc_fids() == [1, 2, 3, 4, 5]
    # the filter is persisted: diffs honour it too
    r = runner.invoke(cli, [*args, "status"])
    assert r.exit_code == 0
    r = runner.invoke(cli, [*args, "checkout", "--spatial-filter", "none"])
    assert r.exit_code == 0, r.output
    assert wc_fids() == list(range(1, 11))
