"""Columnar sidecar index: O(1) FeatureBlock loads for the real diff path
(VERDICT r1 item #3). The routed columnar engine must agree exactly with the
tree-walk engine."""

import os

import numpy as np
import pytest

import kart_tpu.importer.importer as importer_mod
from kart_tpu.diff import sidecar
from kart_tpu.diff.engine import get_feature_diff, get_repo_diff
from kart_tpu.models.dataset import Dataset3

from kart_tpu.geometry import Geometry
from helpers import edit_commit, make_imported_repo


@pytest.fixture
def tiny_sidecar_threshold(monkeypatch):
    monkeypatch.setattr(importer_mod, "SIDECAR_MIN_FEATURES", 5)


def _feature_tree_oid(repo, rev, ds_path="points"):
    ds = repo.structure(rev).datasets[ds_path]
    return ds.feature_tree.oid


def test_import_writes_sidecar(tmp_path, tiny_sidecar_threshold):
    repo, ds_path = make_imported_repo(tmp_path, n=60)
    ds = repo.structure("HEAD").datasets[ds_path]
    assert sidecar.has_sidecar(repo, ds)

    block = sidecar.load_block(repo, ds)
    assert block.count == 60
    assert sorted(block.keys[:60].tolist()) == list(range(1, 61))
    # paths recompute from keys (nothing stored for int pks)
    assert block.path_for_index(0) == ds.path_encoder.encode_pks_to_path(
        (int(block.keys[0]),)
    )

    # sidecar block must equal a tree-walk block
    from kart_tpu.ops.blocks import FeatureBlock

    walked = FeatureBlock.from_dataset(ds)
    np.testing.assert_array_equal(
        block.keys[: block.count], walked.keys[: walked.count]
    )
    np.testing.assert_array_equal(
        block.oids[: block.count], walked.oids[: walked.count]
    )


def test_commit_rolls_sidecar_forward(tmp_path, tiny_sidecar_threshold):
    repo, ds_path = make_imported_repo(tmp_path, n=40)
    edit_commit(
        repo,
        ds_path,
        inserts=[{"fid": 100, "geom": Geometry.from_wkt("POINT (1 1)"), "name": "new", "rating": 1.0}],
        updates=[{"fid": 3, "geom": Geometry.from_wkt("POINT (2 2)"), "name": "upd", "rating": 2.0}],
        deletes=[7],
    )
    new_ds = repo.structure("HEAD").datasets[ds_path]
    # present without any tree walk having run
    assert sidecar.has_sidecar(repo, new_ds)

    block = sidecar.load_block(repo, new_ds)
    keys = set(block.keys[: block.count].tolist())
    assert 100 in keys and 7 not in keys and block.count == 40

    # incremental result == fresh build from the tree
    from kart_tpu.ops.blocks import FeatureBlock

    walked = FeatureBlock.from_dataset(new_ds)
    np.testing.assert_array_equal(
        block.keys[: block.count], walked.keys[: walked.count]
    )
    np.testing.assert_array_equal(
        block.oids[: block.count], walked.oids[: walked.count]
    )


def _diff_as_dict(repo, base, target, engine):
    os.environ["KART_DIFF_ENGINE"] = engine
    try:
        rd = get_repo_diff(repo.structure(base), repo.structure(target))
        out = {}
        for ds_path, ds_diff in rd.items():
            fd = ds_diff.get("feature") or {}
            out[ds_path] = {
                key: (
                    delta.old_value if delta.old else None,
                    delta.new_value if delta.new else None,
                )
                for key, delta in fd.items()
            }
        return out
    finally:
        del os.environ["KART_DIFF_ENGINE"]


def test_routed_columnar_diff_matches_tree_diff(tmp_path, tiny_sidecar_threshold):
    repo, ds_path = make_imported_repo(tmp_path, n=50)
    edit_commit(
        repo,
        ds_path,
        inserts=[{"fid": 900, "geom": Geometry.from_wkt("POINT (5 5)"), "name": "ins", "rating": 0.5}],
        updates=[{"fid": 10, "geom": Geometry.from_wkt("POINT (6 6)"), "name": "u", "rating": 1.5}],
        deletes=[1, 2],
    )
    tree_result = _diff_as_dict(repo, "HEAD^", "HEAD", "tree")
    col_result = _diff_as_dict(repo, "HEAD^", "HEAD", "columnar")
    auto_result = _diff_as_dict(repo, "HEAD^", "HEAD", "auto")
    assert tree_result == col_result == auto_result
    assert set(tree_result[ds_path]) == {900, 10, 1, 2}


def test_columnar_forced_builds_sidecar_lazily(tmp_path):
    # no sidecar written at import (threshold stays 10k)
    repo, ds_path = make_imported_repo(tmp_path, n=30)
    ds = repo.structure("HEAD").datasets[ds_path]
    assert not sidecar.has_sidecar(repo, ds)
    edit_commit(repo, ds_path, deletes=[5])
    tree_result = _diff_as_dict(repo, "HEAD^", "HEAD", "tree")
    col_result = _diff_as_dict(repo, "HEAD^", "HEAD", "columnar")
    assert tree_result == col_result
    # forcing columnar built + cached the sidecars
    assert sidecar.has_sidecar(repo, repo.structure("HEAD").datasets[ds_path])


def test_hash_keyed_sidecar_with_paths(tmp_path, tiny_sidecar_threshold):
    """String-pk datasets store paths in the sidecar (LazyPaths +
    SidecarCapture.add_path_batch): keys are filename hashes and pk recovery
    goes through the stored path."""
    import sqlite3

    from kart_tpu.core.repo import KartRepo
    from kart_tpu.importer import ImportSource
    from kart_tpu.importer.importer import import_sources

    path = str(tmp_path / "strings.gpkg")
    con = sqlite3.connect(path)
    con.executescript(
        """
        CREATE TABLE gpkg_contents (
            table_name TEXT NOT NULL PRIMARY KEY, data_type TEXT NOT NULL,
            identifier TEXT UNIQUE, description TEXT DEFAULT '',
            last_change DATETIME, min_x DOUBLE, min_y DOUBLE,
            max_x DOUBLE, max_y DOUBLE, srs_id INTEGER);
        INSERT INTO gpkg_contents (table_name, data_type, identifier)
            VALUES ('records', 'attributes', 'string-pk records');
        CREATE TABLE records (code TEXT PRIMARY KEY NOT NULL, value INTEGER);
        """
    )
    for i in range(25):
        con.execute("INSERT INTO records VALUES (?, ?)", (f"K{i:03d}", i * 2))
    con.commit()
    con.close()

    repo = KartRepo.init_repository(str(tmp_path / "repo"))
    repo.config.set_many({"user.name": "T", "user.email": "t@example.com"})
    import_sources(repo, ImportSource.open(path))

    ds = list(repo.structure("HEAD").datasets)[0]
    assert ds.path_encoder.scheme != "int"
    assert sidecar.has_sidecar(repo, ds)
    block = sidecar.load_block(repo, ds)
    assert block.count == 25
    pks = {ds.decode_path_to_pks(block.path_for_index(i))[0] for i in range(25)}
    assert pks == {f"K{i:03d}" for i in range(25)}

    # sidecar block equals tree walk (keys + oids)
    from kart_tpu.ops.blocks import FeatureBlock

    walked = FeatureBlock.from_dataset(ds)
    np.testing.assert_array_equal(
        block.keys[: block.count], walked.keys[: walked.count]
    )
    np.testing.assert_array_equal(
        block.oids[: block.count], walked.oids[: walked.count]
    )


def test_schema_change_commit_skips_sidecar_rollforward(
    tmp_path, tiny_sidecar_threshold
):
    """A commit that rewrites schema.json must not roll the sidecar forward
    (blobs are re-encoded under the new schema); the next diff rebuilds."""
    from kart_tpu.diff.structs import (
        DatasetDiff,
        Delta,
        DeltaDiff,
        KeyValue,
        RepoDiff,
    )

    repo, ds_path = make_imported_repo(tmp_path, n=30)
    structure = repo.structure("HEAD")
    ds = structure.datasets[ds_path]
    old_cols = ds.schema.to_column_dicts()
    new_cols = [dict(c) for c in old_cols if c["name"] != "rating"]

    meta_diff = DeltaDiff()
    meta_diff.add_delta(
        Delta.update(
            KeyValue(("schema.json", old_cols)), KeyValue(("schema.json", new_cols))
        )
    )
    feature_diff = DeltaDiff()
    old_f = ds.get_feature([4])
    new_f = {k: v for k, v in old_f.items() if k != "rating"}
    new_f["name"] = "schema-changed"
    feature_diff.add_delta(Delta.update(KeyValue((4, old_f)), KeyValue((4, new_f))))
    ds_diff = DatasetDiff()
    ds_diff["meta"] = meta_diff
    ds_diff["feature"] = feature_diff
    repo_diff = RepoDiff()
    repo_diff[ds_path] = ds_diff
    structure.commit_diff(repo_diff, "drop a column", validate=False)

    new_ds = repo.structure("HEAD").datasets[ds_path]
    # no (possibly poisoned) incremental sidecar was written
    assert not sidecar.has_sidecar(repo, new_ds)
    # and a forced columnar diff (fresh build) matches the tree engine
    tree_result = _diff_as_dict(repo, "HEAD^", "HEAD", "tree")
    col_result = _diff_as_dict(repo, "HEAD^", "HEAD", "columnar")
    assert tree_result == col_result


def test_duplicate_pk_source_sidecar_matches_tree(tmp_path, tiny_sidecar_threshold):
    """Duplicate source pks resolve last-wins in the committed tree; the
    sidecar written from the import capture must mirror that exactly
    (ADVICE r3: a stale duplicate row would later pair against the live
    head in the columnar merge-join and emit a spurious UPDATE)."""
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.importer import ImportSource
    from kart_tpu.importer.importer import import_sources
    from kart_tpu.models.schema import Schema
    from kart_tpu.ops.blocks import FeatureBlock

    class DupSource(ImportSource):
        dest_path = "dup"

        @property
        def schema(self):
            return Schema.from_column_dicts(
                [
                    {
                        "id": "c1",
                        "name": "fid",
                        "dataType": "integer",
                        "size": 64,
                        "primaryKeyIndex": 0,
                    },
                    {"id": "c2", "name": "name", "dataType": "text"},
                ]
            )

        def features(self):
            for i in range(1, 40):
                yield {"fid": i, "name": f"first-{i}"}
            yield {"fid": 5, "name": "winner-5"}
            yield {"fid": 17, "name": "winner-17"}

        @property
        def feature_count(self):
            return 41

    repo = KartRepo.init_repository(tmp_path / "repo")
    repo.config.set_many({"user.name": "t", "user.email": "t@e"})
    import_sources(repo, [DupSource()])
    ds = repo.structure("HEAD").datasets["dup"]
    assert ds.get_feature([5])["name"] == "winner-5"
    assert ds.get_feature([17])["name"] == "winner-17"

    tree_block = FeatureBlock.from_dataset(ds, pad=False)
    assert tree_block.count == 39  # 41 rows, 2 duplicates collapsed
    side_block = sidecar.load_block(repo, ds)
    assert side_block is not None
    assert side_block.count == tree_block.count
    np.testing.assert_array_equal(
        side_block.keys[: side_block.count], tree_block.keys[: tree_block.count]
    )
    np.testing.assert_array_equal(
        side_block.oids[: side_block.count], tree_block.oids[: tree_block.count]
    )
