"""Diff writer option breadth (VERDICT r4 weak #8: thin vs the reference's
test_diff.py): html output, json styles, key filters, multi-dataset geojson
output directories, and writer--crs coverage beyond the basics."""

import json
import os
import re

import pytest
from click.testing import CliRunner

from helpers import create_points_gpkg, edit_commit, make_imported_repo
from kart_tpu.cli import cli


@pytest.fixture
def edited_repo(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=10)
    edit_commit(
        repo, ds_path,
        updates=[
            {**repo.datasets()[ds_path].get_feature([2]), "name": "two!"},
            {**repo.datasets()[ds_path].get_feature([5]), "rating": 9.0},
        ],
        deletes=[7],
        message="edits",
    )
    return repo, ds_path, tmp_path / "repo"


def invoke(repo_dir, *args):
    return CliRunner().invoke(cli, ["-C", str(repo_dir), *args])


class TestHtmlWriter:
    def test_html_diff_writes_file(self, edited_repo, tmp_path):
        repo, ds_path, repo_dir = edited_repo
        out = tmp_path / "diff.html"
        r = invoke(repo_dir, "diff", "HEAD^...HEAD", "-o", "html",
                   "--output", str(out))
        assert r.exit_code == 0, r.output
        html = out.read_text()
        assert html.startswith("<!DOCTYPE html>")
        # embedded geojson data: deltas present with the U-/U+/D id scheme
        m = re.search(r"const DATA = (\{.*?\});\n", html, re.S)
        assert m, html[:200]
        data = json.loads(m.group(1))
        ids = sorted(f["id"] for f in data[ds_path]["features"])
        assert ids == ["D::7", "U+::2", "U+::5", "U-::2", "U-::5"]


class TestJsonStyles:
    def test_styles_same_data_different_bytes(self, edited_repo):
        repo, ds_path, repo_dir = edited_repo
        outs = {}
        for style in ("pretty", "compact", "extracompact"):
            r = invoke(repo_dir, "diff", "HEAD^...HEAD", "-o", "json",
                       "--json-style", style)
            assert r.exit_code == 0, r.output
            outs[style] = r.output
        parsed = {s: json.loads(t) for s, t in outs.items()}
        assert parsed["pretty"] == parsed["compact"] == parsed["extracompact"]
        # pretty is indented; compact styles are single-line-ish
        assert "\n  " in outs["pretty"]
        assert "\n  " not in outs["compact"]
        assert len(outs["compact"]) < len(outs["pretty"])

    def test_show_and_create_patch_styles(self, edited_repo):
        repo, ds_path, repo_dir = edited_repo
        r = invoke(repo_dir, "show", "-o", "json", "--json-style", "compact")
        assert r.exit_code == 0, r.output
        body = json.loads(r.output)
        assert "kart.diff/v1+hexwkb" in body and "kart.show/v1" in body
        r = invoke(repo_dir, "create-patch", "HEAD")
        assert r.exit_code == 0, r.output
        patch = json.loads(r.output)
        assert "kart.patch/v1" in patch


class TestKeyFilters:
    def test_single_pk_filter(self, edited_repo):
        repo, ds_path, repo_dir = edited_repo
        r = invoke(repo_dir, "diff", "HEAD^...HEAD", "-o", "json",
                   f"{ds_path}:2")
        assert r.exit_code == 0, r.output
        feats = json.loads(r.output)["kart.diff/v1+hexwkb"][ds_path]["feature"]
        assert len(feats) == 1 and feats[0]["+"]["fid"] == 2

    def test_multiple_pk_filters(self, edited_repo):
        repo, ds_path, repo_dir = edited_repo
        r = invoke(repo_dir, "diff", "HEAD^...HEAD", "-o", "json",
                   f"{ds_path}:2", f"{ds_path}:7")
        feats = json.loads(r.output)["kart.diff/v1+hexwkb"][ds_path]["feature"]
        fids = sorted(
            (d.get("+") or d.get("-"))["fid"] for d in feats
        )
        assert fids == [2, 7]

    def test_dataset_filter_excludes_others(self, tmp_path):
        # two datasets; filtering one must hide the other entirely
        from kart_tpu.core.repo import KartRepo
        from kart_tpu.importer import ImportSource
        from kart_tpu.importer.importer import import_sources

        repo = KartRepo.init_repository(tmp_path / "repo")
        repo.config.set_many({"user.name": "t", "user.email": "t@e"})
        g1 = create_points_gpkg(str(tmp_path / "a.gpkg"), n=4, table="alpha")
        g2 = create_points_gpkg(str(tmp_path / "b.gpkg"), n=4, table="beta")
        import_sources(repo, ImportSource.open(g1))
        import_sources(repo, ImportSource.open(g2))
        edit_commit(
            repo, "alpha",
            updates=[{**repo.datasets()["alpha"].get_feature([1]), "name": "x"}],
            message="a-edit",
        )
        edit_commit(
            repo, "beta",
            updates=[{**repo.datasets()["beta"].get_feature([1]), "name": "y"}],
            message="b-edit",
        )
        r = invoke(tmp_path / "repo", "diff", "HEAD~2...HEAD", "-o", "json",
                   "alpha")
        body = json.loads(r.output)["kart.diff/v1+hexwkb"]
        assert "alpha" in body and "beta" not in body


class TestGeojsonMultiDataset:
    def test_requires_output_dir(self, tmp_path):
        from kart_tpu.core.repo import KartRepo
        from kart_tpu.importer import ImportSource
        from kart_tpu.importer.importer import import_sources

        repo = KartRepo.init_repository(tmp_path / "repo")
        repo.config.set_many({"user.name": "t", "user.email": "t@e"})
        for table in ("alpha", "beta"):
            g = create_points_gpkg(
                str(tmp_path / f"{table}.gpkg"), n=3, table=table
            )
            import_sources(repo, ImportSource.open(g))
        for table in ("alpha", "beta"):
            edit_commit(
                repo, table,
                updates=[
                    {**repo.datasets()[table].get_feature([1]), "name": "x"}
                ],
                message=f"{table}-edit",
            )
        r = invoke(tmp_path / "repo", "diff", "HEAD~2...HEAD", "-o", "geojson")
        assert r.exit_code != 0
        assert "directory" in r.output.lower()
        outdir = tmp_path / "out"
        r = invoke(tmp_path / "repo", "diff", "HEAD~2...HEAD", "-o", "geojson",
                   "--output", str(outdir))
        assert r.exit_code == 0, r.output
        files = sorted(os.listdir(outdir))
        assert files == ["alpha.geojson", "beta.geojson"]
        fc = json.loads((outdir / "alpha.geojson").read_text())
        assert fc["type"] == "FeatureCollection" and len(fc["features"]) == 2


class TestCrsOnWriters:
    @pytest.mark.parametrize("fmt", ["json", "geojson", "json-lines"])
    def test_crs_reprojects(self, edited_repo, fmt, tmp_path):
        repo, ds_path, repo_dir = edited_repo
        r = invoke(repo_dir, "diff", "HEAD^...HEAD", "-o", fmt,
                   "--crs", "EPSG:3857")
        assert r.exit_code == 0, r.output
        # web-mercator coordinates are in the millions of metres here
        assert re.search(r"1[01]\d{5,}", r.output), r.output[:300]

    def test_invalid_crs_fails(self, edited_repo):
        repo, ds_path, repo_dir = edited_repo
        r = invoke(repo_dir, "diff", "HEAD^...HEAD", "-o", "json",
                   "--crs", "EPSG:999999")
        assert r.exit_code != 0
