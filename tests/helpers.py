"""Test fixture builders: tiny GeoPackages made with raw sqlite3 (mirroring
the reference's tests/data/*.tgz known-answer style, SURVEY.md §4)."""

import sqlite3
import struct

from kart_tpu.crs import NZTM_WKT, WGS84_WKT


def gpkg_point(x, y, srs_id=4326):
    """Minimal GPKG binary for a 2D point."""
    header = b"GP\x00\x01" + struct.pack("<i", srs_id)
    wkb = struct.pack("<BI2d", 1, 1, x, y)
    return header + wkb


def create_points_gpkg(path, n=10, *, table="points", srs_id=4326):
    """A GPKG with n point features: fid pk, geom, name text, rating real."""
    con = sqlite3.connect(path)
    con.executescript(
        """
        CREATE TABLE gpkg_contents (
            table_name TEXT NOT NULL PRIMARY KEY, data_type TEXT NOT NULL,
            identifier TEXT UNIQUE, description TEXT DEFAULT '',
            last_change DATETIME, min_x DOUBLE, min_y DOUBLE,
            max_x DOUBLE, max_y DOUBLE, srs_id INTEGER);
        CREATE TABLE gpkg_geometry_columns (
            table_name TEXT NOT NULL, column_name TEXT NOT NULL,
            geometry_type_name TEXT NOT NULL, srs_id INTEGER NOT NULL,
            z TINYINT NOT NULL, m TINYINT NOT NULL,
            CONSTRAINT pk_geom_cols PRIMARY KEY (table_name, column_name));
        CREATE TABLE gpkg_spatial_ref_sys (
            srs_name TEXT NOT NULL, srs_id INTEGER NOT NULL PRIMARY KEY,
            organization TEXT NOT NULL, organization_coordsys_id INTEGER NOT NULL,
            definition TEXT NOT NULL, description TEXT);
        """
    )
    wkt = WGS84_WKT if srs_id == 4326 else NZTM_WKT
    con.execute(
        "INSERT INTO gpkg_spatial_ref_sys VALUES (?, ?, 'EPSG', ?, ?, NULL)",
        ("WGS 84" if srs_id == 4326 else "NZTM", srs_id, srs_id, wkt),
    )
    con.execute(
        "INSERT INTO gpkg_contents (table_name, data_type, identifier, srs_id) "
        "VALUES (?, 'features', ?, ?)",
        (table, f"{table} title", srs_id),
    )
    con.execute(
        "INSERT INTO gpkg_geometry_columns VALUES (?, 'geom', 'POINT', ?, 0, 0)",
        (table, srs_id),
    )
    con.execute(
        f"CREATE TABLE {table} ("
        "fid INTEGER PRIMARY KEY AUTOINCREMENT NOT NULL, "
        "geom POINT, name TEXT, rating REAL)"
    )
    for i in range(1, n + 1):
        con.execute(
            f"INSERT INTO {table} (fid, geom, name, rating) VALUES (?, ?, ?, ?)",
            (i, gpkg_point(100.0 + i, -40.0 - i * 0.1, srs_id), f"feature-{i}", i / 2.0),
        )
    con.commit()
    con.close()
    return path


def create_attributes_gpkg(path, n=5, *, table="records"):
    """A geometry-less (attributes) GPKG table."""
    con = sqlite3.connect(path)
    con.executescript(
        """
        CREATE TABLE gpkg_contents (
            table_name TEXT NOT NULL PRIMARY KEY, data_type TEXT NOT NULL,
            identifier TEXT UNIQUE, description TEXT DEFAULT '',
            last_change DATETIME, min_x DOUBLE, min_y DOUBLE,
            max_x DOUBLE, max_y DOUBLE, srs_id INTEGER);
        """
    )
    con.execute(
        "INSERT INTO gpkg_contents (table_name, data_type, identifier) "
        "VALUES (?, 'attributes', ?)",
        (table, table),
    )
    con.execute(
        f"CREATE TABLE {table} ("
        "id INTEGER PRIMARY KEY NOT NULL, code TEXT, amount MEDIUMINT, flag BOOLEAN)"
    )
    for i in range(1, n + 1):
        con.execute(
            f"INSERT INTO {table} VALUES (?, ?, ?, ?)",
            (i, f"C{i:03d}", i * 100, i % 2),
        )
    con.commit()
    con.close()
    return path


def make_imported_repo(tmp_path, *, n=10):
    """init + import points.gpkg -> (repo, ds_path)."""
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.importer import ImportSource
    from kart_tpu.importer.importer import import_sources

    gpkg = create_points_gpkg(str(tmp_path / "points.gpkg"), n=n)
    repo = KartRepo.init_repository(tmp_path / "repo")
    repo.config.set_many({"user.name": "Tester", "user.email": "t@example.com"})
    sources = ImportSource.open(gpkg)
    import_sources(repo, sources)
    return repo, "points"


def edit_commit(repo, ds_path, *, inserts=(), updates=(), deletes=(), message="edit features", ref="HEAD"):
    """Build a feature diff and commit it; -> commit oid (shared helper in
    kart_tpu.synth — bench.py's storm workers use the same one)."""
    from kart_tpu.synth import commit_feature_edits

    return commit_feature_edits(
        repo, ds_path, inserts=inserts, updates=updates, deletes=deletes,
        message=message, ref=ref,
    )


def make_repo_with_edits(tmp_path, *, n=40):
    """init + import + one edit commit -> (repo_path, expected edit counts).

    The canonical two-commit repo for CLI diff tests (the reference's
    1-insert/2-update/5-delete edit fixture shape, tests/conftest.py:814-900)."""
    repo, ds_path = make_imported_repo(tmp_path, n=n)
    inserts = [
        {"fid": n + 1, "geom": None, "name": "new-a", "rating": 9.5},
    ]
    updates = [
        {"fid": 2, "geom": None, "name": "renamed-2", "rating": 0.5},
        {"fid": 5, "geom": None, "name": "renamed-5", "rating": 1.5},
    ]
    deletes = [7, 11, 13]
    edit_commit(repo, ds_path, inserts=inserts, updates=updates, deletes=deletes)
    return str(repo.workdir or repo.gitdir), {
        "inserts": len(inserts),
        "updates": len(updates),
        "deletes": len(deletes),
    }


def wc_connect(path):
    """Open a GPKG working copy for raw SQL edits: registers the GPKG
    envelope functions the rtree-extension triggers call (real editing
    clients get these from spatialite/GDAL)."""
    import sqlite3

    from kart_tpu.workingcopy.gpkg import _register_gpkg_functions

    con = sqlite3.connect(str(path))
    _register_gpkg_functions(con)
    return con
