"""Bench-schema guard (ISSUE 1 satellite, tier-1): every BENCH_r*.json key
the ROADMAP/VERDICT record cites must still be emitted by `python bench.py`
— plus this round's new keys — so headline numbers can't silently drop out
of the record. Static check: bench.py writes every key as a string literal,
so a missing literal means the metric was dropped or renamed."""

import glob
import json
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: keys added by ISSUE 1 (block-pruned spatial diffs + fused
#: materialisation + satellite measurements)
NEW_KEYS = [
    "cli_100m_fulldiff_seconds",
    "cli_100m_fulldiff_cold_seconds",
    "cli_100m_fulldiff_rows_materialised",
    "cli_100m_spatial_unpruned_seconds",
    "cli_100m_spatial_output_matches_unpruned",
    "bbox_f32_envelopes_per_sec",
    "bbox_f32_seconds",
    "bbox_f32_vs_numpy",
    "bbox_packed_seconds",
    "bbox_f32_vs_packed",
    "wc_checkout_seconds",
    "wc_checkout_features_per_sec",
    "wc_reset_seconds",
    "reference_checkout_rate",
    "wc_checkout_vs_reference",
    "import_phase_source_read_seconds",
    "import_phase_encode_seconds",
    "import_phase_hash_deflate_seconds",
    "import_phase_tree_build_seconds",
    "import_serial_seconds",
]

#: keys added by ISSUE 2 (fault-tolerant transport: the fetch-resume
#: robustness metric — a killed transfer must cost a remainder, not a
#: restart)
NEW_KEYS += [
    "fetch_resume_seconds",
    "fetch_resume_objects_total",
    "fetch_resume_objects_salvaged",
    "fetch_resume_objects_resent",
]

#: keys added by ISSUE 3 (telemetry subsystem: the honesty metric — the
#: disabled instrumentation's measured cost on the 1M-row diff path)
NEW_KEYS += [
    "telemetry_overhead_pct",
    "telemetry_noop_ns_per_call",
    "telemetry_calls_per_diff",
    "telemetry_diff_rows",
]

#: keys added by ISSUE 4 (static-analysis suite: `kart lint` full-tree
#: runtime + active rule/file/finding counts — the lint rule KTL007 checks
#: the reverse direction, bench keys without a guard entry)
NEW_KEYS += [
    "lint_runtime_seconds",
    "lint_rules_total",
    "lint_files_scanned",
    "lint_findings_total",
]

#: keys added by ISSUE 5 (pipelined import: the measured pipeline-vs-serial
#: overlap win at 1M rows, and a real 10M import leg so the 100M
#: extrapolation is no longer a guess)
NEW_KEYS += [
    "import_pipeline_seconds",
    "import_pipeline_speedup",
    "cli_10m_import_rows",
    "cli_10m_import_seconds",
    "import_features_per_sec_10m",
]

#: keys added by ISSUE 6 (sharded multi-device diff backend: the
#: `bench.py --multichip` scaling sweep — 1-dev = the monolithic
#: single-device kernel, 2/4/8-dev = the sharded record-batch path — plus
#: the probe-verdict-cache honesty flag and the measured environment
#: ceilings that contextualise a core-starved container's curve). These
#: land in MULTICHIP_r*.json rather than BENCH_r*.json, but the same
#: drop-out guard applies.
NEW_KEYS += [
    "multichip_rows",
    "multichip_classify_rows_per_sec_1dev",
    "multichip_classify_rows_per_sec_1dev_batched",
    "multichip_classify_rows_per_sec_2dev",
    "multichip_classify_rows_per_sec_4dev",
    "multichip_classify_rows_per_sec_8dev",
    "multichip_scaling_1to2",
    "multichip_scaling_1to4",
    "multichip_counts_exact",
    "multichip_host_cores",
    "multichip_kernel",
    "multichip_env_alu_2proc_scaling",
    "multichip_env_memcpy_2proc_scaling",
    "backend_probe_cached",
    # MULTICHIP record continuity fields (the driver's r01-r05 schema)
    "ok",
    "skipped",
]

#: keys added by ISSUE 7 (`bench.py --serve-storm`: aggregate concurrent
#: clone throughput vs the serial cache-disabled baseline, tail latency,
#: the enum-cache hit rate scraped from /api/v1/stats, and the
#: kill-the-server-mid-storm leg where every client must resume to
#: completion). Recorded in BENCH_r07.json.
NEW_KEYS += [
    "serve_storm_rows",
    "serve_storm_clients",
    "serve_storm_requests_total",
    "serve_storm_agg_features_per_sec",
    "serve_storm_serial_features_per_sec",
    "serve_storm_speedup_vs_serial",
    "serve_storm_p99_request_seconds",
    "serve_enum_cache_hit_rate",
    "serve_storm_fault_clients",
    "serve_storm_fault_clients_ok",
    # the env-ceiling context leg (same total requests, as many colocated
    # clients as the host's cores can actually run concurrently)
    "serve_storm_ceiling_clients",
    "serve_storm_ceiling_agg_features_per_sec",
    "serve_storm_ceiling_speedup_vs_serial",
]


#: keys added by ISSUE 9 (`bench.py --merge-storm`: K contending writers on
#: one branch through the server-side auto-rebase + merge queue — commits
#: landed/s, retry amplification (client wire attempts / commits landed),
#: client-visible CAS failures (must be 0), queue waits, the
#: overlapping-feature conflict leg (terminal after exactly one attempt),
#: and the SIGKILL-the-server-mid-storm leg). Recorded in BENCH_r09.json.
NEW_KEYS += [
    "merge_storm_writers",
    "merge_storm_commits_total",
    "merge_storm_commits_landed",
    "merge_storm_commits_per_sec",
    "merge_storm_client_attempts",
    "merge_storm_retry_amplification",
    "merge_storm_cas_failures_client_visible",
    "merge_storm_queue_p99_wait_seconds",
    "merge_storm_queue_mean_wait_seconds",
    "merge_storm_rebases_landed",
    "rebase_conflict_writers",
    "rebase_conflict_rejections",
    "rebase_conflict_attempts_per_reject",
    "merge_storm_fault_writers",
    "merge_storm_fault_writers_ok",
]


#: keys added by ISSUE 10 (`bench.py --tiles`: tile read-serving off the
#: columnar store — tiles/s cold (fresh cache, block-pruned selection +
#: vectorized clip/quantize) and cached (commit-addressed memo, zero ODB
#: touches), the pruning evidence (blocks read per tile must be ≪ the
#: dataset's block count), byte-identity cold vs cached, and the
#: concurrent-client tile storm against a real `kart serve` process).
#: Recorded in BENCH_r10.json.
NEW_KEYS += [
    "tile_rows",
    "tile_zoom",
    "tile_count",
    "tile_synth_seconds",
    "tiles_per_sec_cold",
    "tiles_per_sec_cached",
    "tile_payload_identical",
    "tile_cache_hit_rate",
    "tile_blocks_total",
    "tile_blocks_read_mean",
    "tile_blocks_pruned_pct",
    "tile_features_mean",
    "tile_storm_clients",
    "tile_storm_requests_total",
    "tile_storm_ok_requests",
    "tile_storm_agg_tiles_per_sec",
    "tile_storm_p99_request_seconds",
]


#: keys added by ISSUE 11 (concurrency & device-purity analyzer: the
#: per-rule timing headline — the slowest rule's wall-clock, recorded so
#: the <5s full-tree bound stays attributable as the rule count grows)
NEW_KEYS += [
    "lint_rule_seconds_max",
]


#: keys added by ISSUE 19 (wire-taint dataflow analyzer: the KTL030-034
#: engine's coverage headline — function bodies analyzed in the taint
#: pass; a drop means the declared wire surface silently shrank)
NEW_KEYS += [
    "lint_taint_functions_analyzed",
]


#: keys added by ISSUE 12 (request-scoped observability: the storm bench
#: now also reads the *server-reported* per-verb latency quantiles from
#: the new bucketed histograms and checks they agree with the
#: client-measured percentiles within the documented one-bucket error
#: bound — the server's tail latency is a first-class number, not a
#: client-side recomputation)
NEW_KEYS += [
    "serve_storm_server_p50_seconds",
    "serve_storm_server_p99_seconds",
    "serve_storm_server_p99_bucket_distance",
    "serve_storm_server_p99_agrees",
    # the coupled-regime agreement leg (serial, uncached: each request is
    # dominated by the server's own walk, so server-estimated and
    # client-measured p99 must land within one log bucket)
    "serve_serial_server_p99_seconds",
    "serve_serial_p99_bucket_distance",
    "serve_serial_server_p99_agrees",
]


#: keys added by ISSUE 13 (`bench.py --fleet`: a primary + M pull-replicas
#: serving N clients — aggregate cached tiles/s across the replica fleet
#: vs the single-node BENCH_r10 cached number, peer-cache hit rate,
#: aggregate clone throughput fanned across replicas, replication lag
#: (push-ack → replica-visible) p99, and the failover drill: the primary
#: SIGKILLed mid-write-storm must lose zero acked commits and the
#: replicas must converge byte-identical). Recorded in BENCH_r13.json.
NEW_KEYS += [
    "fleet_rows",
    "fleet_replicas",
    "fleet_synth_seconds",
    "fleet_initial_sync_seconds",
    "fleet_tile_clients",
    "fleet_tile_requests_total",
    "fleet_tile_ok_requests",
    "fleet_agg_tiles_per_sec",
    "fleet_tile_p99_request_seconds",
    "fleet_peer_cache_hit_rate",
    "fleet_tiles_vs_single_node_cached",
    "fleet_tiles_beats_single_node",
    "fleet_clone_clients",
    "fleet_clone_ok",
    "fleet_agg_clone_features_per_sec",
    "fleet_lag_pushes",
    "fleet_replication_lag_p99_seconds",
    "fleet_replication_lag_mean_seconds",
    "fleet_failover_commits_acked",
    "fleet_failover_restarted",
    "fleet_failover_lost_commits",
    "fleet_replicas_converged_identical",
]

#: ISSUE 14 — bench.py --live (live-update events; BENCH_r14)
NEW_KEYS += [
    "live_rows",
    "live_watchers",
    "live_pushes",
    "live_synth_seconds",
    "live_watchers_served",
    "live_events_total",
    "live_invalidation_p99_seconds",
    "live_invalidation_mean_seconds",
    "live_warm_requests",
    "live_warm_hit_rate",
    "live_warm_cold_encodes",
    "live_dirty_tiles_exact_events",
    "live_dirty_tiles_exact",
    "live_replica_lag_p99_seconds",
    "live_replica_lag_mean_seconds",
    "live_replica_lag_vs_polled_p99",
    "live_replica_lag_beats_polled",
]

#: ISSUE 15 — the KTB2/MVT encoding ladder and the parallel pyramid
#: export (bench.py --tiles extensions)
NEW_KEYS += [
    "tile_bytes_per_feature_ktb1",
    "tile_bytes_per_feature_ktb2",
    "tile_bytes_per_feature_mvt",
    "tiles_per_sec_ktb2_cold",
    "tile_ktb2_vs_ktb1",
    "tile_ktb2_meets_2x",
    "pyramid_export_zoom",
    "pyramid_export_tiles",
    "pyramid_export_seconds_1w",
    "pyramid_export_seconds_nw",
    "pyramid_export_workers",
    "pyramid_export_speedup",
    "pyramid_export_identical",
    "pyramid_export_env_ceiling",
]

#: keys added by ISSUE 16 (predicate-pushdown scans + the device-parallel
#: cross-commit spatial join + the 2-replica fleet scatter)
NEW_KEYS += [
    "query_scan_rows",
    "query_scan_synth_seconds",
    "query_scan_seconds",
    "query_scan_rows_per_sec",
    "query_scan_unpruned_seconds",
    "query_scan_rows_per_sec_unpruned",
    "query_scan_matches",
    "query_scan_pruned_matches_unpruned",
    "query_scan_block_prune_fraction",
    "query_scan_prune_meets_95pct",
    "query_scan_prune_speedup",
    "query_join_probe_rows",
    "query_join_build_rows",
    "query_join_pairs",
    "query_join_host_seconds",
    "query_join_pairs_per_sec_100m_x_1m_host",
    "query_join_device_seconds",
    "query_join_pairs_per_sec_100m_x_1m",
    "query_join_device_vs_host",
    "query_join_device_matches_host",
    "query_scatter_rows",
    "query_scatter_synth_seconds",
    "query_join_single_node_seconds",
    "query_join_scatter2_seconds",
    "query_join_pairs_per_sec_100m_x_1m_scatter2",
    "query_scatter_speedup",
    "query_scatter_matches_single",
    "query_scatter_parts",
]

#: keys added by ISSUE 20 (exact geometry end-to-end: the refine stage's
#: price on the pushdown scan, the refine kernel bbox-only vs host vs
#: device with bit-identity asserted, and the `geom` tile layer's
#: bytes/feature + cold encode rate next to the r15 encoding ladder)
NEW_KEYS += [
    "query_scan_approx_seconds",
    "query_scan_refine_pairs",
    "query_scan_refine_overhead",
    "query_scan_exact_matches_approx",
    "query_refine_pairs",
    "query_refine_matches",
    "query_refine_pairs_per_sec_bbox_only",
    "query_refine_pairs_per_sec_host",
    "query_refine_pairs_per_sec_device",
    "query_refine_exact_vs_bbox_cost",
    "query_refine_device_vs_host",
    "query_refine_device_matches_host",
    "tile_bytes_per_feature_geom",
    "tiles_per_sec_geom_cold",
]


def test_bench_emits_every_recorded_key():
    with open(os.path.join(REPO_ROOT, "bench.py")) as f:
        src = f.read()

    records = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    assert records, "no BENCH_r*.json records found"
    with open(records[-1]) as f:
        latest = json.load(f)
    cited = set(latest.get("parsed", {})) | set(NEW_KEYS)

    missing = sorted(k for k in cited if f'"{k}"' not in src)
    assert not missing, (
        f"bench.py no longer emits recorded metric keys: {missing} — "
        "headline numbers must not silently drop out of the record"
    )


def test_new_keys_not_yet_in_old_records_is_ok():
    """The guard list itself stays valid: every NEW_KEY literal exists in
    bench.py (catches typos in this test's own list)."""
    with open(os.path.join(REPO_ROOT, "bench.py")) as f:
        src = f.read()
    missing = sorted(k for k in NEW_KEYS if f'"{k}"' not in src)
    assert not missing, missing

#: keys measured by the r01-r06 era full `python bench.py` runs, pinned
#: via the then-latest BENCH record until BENCH_r07 (a storm-only record)
#: became the latest — pinned explicitly now so the guard no longer
#: depends on WHICH record is newest
NEW_KEYS += [
    "backend",
    "backend_init_seconds",
    "backend_probe_attempts_utc",
    "backend_probe_error",
    "bbox_e2e_seconds",
    "bbox_envelopes_per_sec",
    "bbox_kernel_seconds",
    "bbox_kernel_vs_numpy",
    "bbox_numpy_seconds",
    "bbox_resident_beats_numpy",
    "bbox_resident_repeat_seconds",
    "bbox_rows",
    "cli_100m_diff_cold_seconds",
    "cli_100m_diff_host_engine_seconds",
    "cli_100m_diff_seconds",
    "cli_100m_north_star_met",
    "cli_100m_rows",
    "cli_100m_spatial_beats_r4_bar",
    "cli_100m_spatial_beats_unfiltered",
    "cli_100m_spatial_diff_cold_seconds",
    "cli_100m_spatial_diff_seconds",
    "cli_100m_synth_seconds",
    "cli_10m_polygon_diff_cold_seconds",
    "cli_10m_polygon_diff_seconds",
    "cli_diff_columnar_cold_seconds",
    "cli_diff_columnar_seconds",
    "cli_diff_rows",
    "cli_diff_rows_per_sec",
    "cli_diff_tree_seconds",
    "cli_import_seconds",
    "cli_import_seconds_median",
    "device_kernel_rate",
    "device_kind",
    "estimation_error_pct",
    "estimation_rows",
    "estimation_seconds",
    "features_materialised_per_sec",
    "host_native_rate",
    "host_native_vs_reference",
    "import_features_per_sec",
    "materialise_vs_reference",
    "merge_classify_seconds",
    "merge_conflict_rows",
    "merge_conflicts_per_sec",
    "merge_index_read_seconds",
    "merge_index_write_seconds",
    "merge_materialise_seconds",
    "metric",
    "n_devices",
    "numpy_twin_rate",
    "poly_rows",
    "poly_synth_seconds",
    "reference_loop_rate",
    "reference_materialise_rate",
    "unit",
    "value",
    "vs_baseline",
    "vs_numpy_twin",
]
