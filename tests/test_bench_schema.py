"""Bench-schema guard (ISSUE 1 satellite, tier-1): every BENCH_r*.json key
the ROADMAP/VERDICT record cites must still be emitted by `python bench.py`
— plus this round's new keys — so headline numbers can't silently drop out
of the record. Static check: bench.py writes every key as a string literal,
so a missing literal means the metric was dropped or renamed."""

import glob
import json
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: keys added by ISSUE 1 (block-pruned spatial diffs + fused
#: materialisation + satellite measurements)
NEW_KEYS = [
    "cli_100m_fulldiff_seconds",
    "cli_100m_fulldiff_cold_seconds",
    "cli_100m_fulldiff_rows_materialised",
    "cli_100m_spatial_unpruned_seconds",
    "cli_100m_spatial_output_matches_unpruned",
    "bbox_f32_envelopes_per_sec",
    "bbox_f32_seconds",
    "bbox_f32_vs_numpy",
    "bbox_packed_seconds",
    "bbox_f32_vs_packed",
    "wc_checkout_seconds",
    "wc_checkout_features_per_sec",
    "wc_reset_seconds",
    "reference_checkout_rate",
    "wc_checkout_vs_reference",
    "import_phase_source_read_seconds",
    "import_phase_encode_seconds",
    "import_phase_hash_deflate_seconds",
    "import_phase_tree_build_seconds",
    "import_serial_seconds",
]

#: keys added by ISSUE 2 (fault-tolerant transport: the fetch-resume
#: robustness metric — a killed transfer must cost a remainder, not a
#: restart)
NEW_KEYS += [
    "fetch_resume_seconds",
    "fetch_resume_objects_total",
    "fetch_resume_objects_salvaged",
    "fetch_resume_objects_resent",
]

#: keys added by ISSUE 3 (telemetry subsystem: the honesty metric — the
#: disabled instrumentation's measured cost on the 1M-row diff path)
NEW_KEYS += [
    "telemetry_overhead_pct",
    "telemetry_noop_ns_per_call",
    "telemetry_calls_per_diff",
    "telemetry_diff_rows",
]

#: keys added by ISSUE 4 (static-analysis suite: `kart lint` full-tree
#: runtime + active rule/file/finding counts — the lint rule KTL007 checks
#: the reverse direction, bench keys without a guard entry)
NEW_KEYS += [
    "lint_runtime_seconds",
    "lint_rules_total",
    "lint_files_scanned",
    "lint_findings_total",
]

#: keys added by ISSUE 5 (pipelined import: the measured pipeline-vs-serial
#: overlap win at 1M rows, and a real 10M import leg so the 100M
#: extrapolation is no longer a guess)
NEW_KEYS += [
    "import_pipeline_seconds",
    "import_pipeline_speedup",
    "cli_10m_import_rows",
    "cli_10m_import_seconds",
    "import_features_per_sec_10m",
]

#: keys added by ISSUE 6 (sharded multi-device diff backend: the
#: `bench.py --multichip` scaling sweep — 1-dev = the monolithic
#: single-device kernel, 2/4/8-dev = the sharded record-batch path — plus
#: the probe-verdict-cache honesty flag and the measured environment
#: ceilings that contextualise a core-starved container's curve). These
#: land in MULTICHIP_r*.json rather than BENCH_r*.json, but the same
#: drop-out guard applies.
NEW_KEYS += [
    "multichip_rows",
    "multichip_classify_rows_per_sec_1dev",
    "multichip_classify_rows_per_sec_1dev_batched",
    "multichip_classify_rows_per_sec_2dev",
    "multichip_classify_rows_per_sec_4dev",
    "multichip_classify_rows_per_sec_8dev",
    "multichip_scaling_1to2",
    "multichip_scaling_1to4",
    "multichip_counts_exact",
    "multichip_host_cores",
    "multichip_kernel",
    "multichip_env_alu_2proc_scaling",
    "multichip_env_memcpy_2proc_scaling",
    "backend_probe_cached",
    # MULTICHIP record continuity fields (the driver's r01-r05 schema)
    "ok",
    "skipped",
]


def test_bench_emits_every_recorded_key():
    with open(os.path.join(REPO_ROOT, "bench.py")) as f:
        src = f.read()

    records = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    assert records, "no BENCH_r*.json records found"
    with open(records[-1]) as f:
        latest = json.load(f)
    cited = set(latest.get("parsed", {})) | set(NEW_KEYS)

    missing = sorted(k for k in cited if f'"{k}"' not in src)
    assert not missing, (
        f"bench.py no longer emits recorded metric keys: {missing} — "
        "headline numbers must not silently drop out of the record"
    )


def test_new_keys_not_yet_in_old_records_is_ok():
    """The guard list itself stays valid: every NEW_KEY literal exists in
    bench.py (catches typos in this test's own list)."""
    with open(os.path.join(REPO_ROOT, "bench.py")) as f:
        src = f.read()
    missing = sorted(k for k in NEW_KEYS if f'"{k}"' not in src)
    assert not missing, missing
