"""Transport: pack format, clone/fetch/push/pull, shallow + filtered partial
clone, promisor fetch (reference behaviors: kart/clone.py, kart/cli.py:211-253,
kart/promisor_utils.py; tested against local-directory remotes exactly like
the reference's own test suite, SURVEY.md §4)."""

import io
import os

import pytest

from kart_tpu import transport
from kart_tpu.core.odb import ObjectMissing, ObjectPromised
from kart_tpu.core.repo import KartRepo
from kart_tpu.transport.pack import PackFormatError, read_pack, write_pack
from kart_tpu.transport.remote import RemoteError, read_shallow

from helpers import edit_commit, make_imported_repo


@pytest.fixture()
def source_repo(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=10)
    edit_commit(
        repo,
        ds_path,
        updates=[{"fid": 1, "geom": None, "name": "renamed", "rating": 9.0}],
        message="second commit",
    )
    return repo, ds_path


def test_pack_roundtrip():
    objects = [
        ("blob", b"hello"),
        ("commit", b"tree abc\n\nmsg\n"),
        ("tree", b""),
    ]
    buf = io.BytesIO()
    assert write_pack(buf, iter(objects)) == 3
    buf.seek(0)
    assert list(read_pack(buf)) == objects


def test_pack_detects_corruption():
    buf = io.BytesIO()
    write_pack(buf, [("blob", b"data")])
    raw = bytearray(buf.getvalue())
    raw[len(raw) // 2] ^= 0xFF
    with pytest.raises((PackFormatError, Exception)):
        list(read_pack(io.BytesIO(bytes(raw))))


def test_clone_full(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(
        repo.workdir, tmp_path / "clone", do_checkout=False
    )
    assert clone.head_commit_oid == repo.head_commit_oid
    # full object transfer: every feature readable
    ds = clone.datasets("HEAD")[ds_path]
    features = list(ds.features())
    assert len(features) == 10
    assert clone.refs.get("refs/remotes/origin/main") == repo.head_commit_oid
    # history came over
    assert len(list(clone.walk_commits(clone.head_commit_oid))) == 2


def test_clone_sets_upstream_config(source_repo, tmp_path):
    repo, _ = source_repo
    clone = transport.clone(repo.workdir, tmp_path / "clone", do_checkout=False)
    assert clone.config.get("branch.main.remote") == "origin"
    assert clone.config.get("remote.origin.url") == repo.workdir


def test_fetch_updates_remote_refs(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(repo.workdir, tmp_path / "clone", do_checkout=False)
    # source advances
    new_oid = edit_commit(
        repo, ds_path, deletes=[2], message="delete feature 2"
    )
    updated = transport.fetch(clone, "origin")
    assert updated.get("refs/remotes/origin/main") == new_oid
    assert clone.odb.contains(new_oid)
    # local branch untouched (fetch is not pull)
    assert clone.head_commit_oid != new_oid


def test_push_fast_forward(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(repo.workdir, tmp_path / "clone", do_checkout=False)
    clone.config.set_many({"user.name": "Cloner", "user.email": "c@example.com"})
    new_oid = edit_commit(
        clone, ds_path, deletes=[3], message="delete feature 3"
    )
    updated = transport.push(clone, "origin")
    assert updated == {"refs/heads/main": new_oid}
    assert repo.refs.get("refs/heads/main") == new_oid
    assert repo.odb.contains(new_oid)


def test_push_non_ff_rejected_then_forced(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(repo.workdir, tmp_path / "clone", do_checkout=False)
    clone.config.set_many({"user.name": "Cloner", "user.email": "c@example.com"})
    # diverge both sides
    edit_commit(repo, ds_path, deletes=[4], message="upstream change")
    edit_commit(clone, ds_path, deletes=[5], message="local change")
    with pytest.raises(RemoteError, match="non-fast-forward"):
        transport.push(clone, "origin")
    transport.push(clone, "origin", force=True)
    assert repo.refs.get("refs/heads/main") == clone.head_commit_oid


def test_push_delete_refspec(source_repo, tmp_path):
    repo, _ = source_repo
    repo.refs.set("refs/heads/topic", repo.head_commit_oid)
    clone = transport.clone(repo.workdir, tmp_path / "clone", do_checkout=False)
    transport.push(clone, "origin", [":topic"])
    assert repo.refs.get("refs/heads/topic") is None


def test_shallow_clone(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(
        repo.workdir, tmp_path / "clone", depth=1, do_checkout=False
    )
    tip = clone.head_commit_oid
    assert tip == repo.head_commit_oid
    # only the tip commit exists; its parent wasn't fetched
    tip_commit = clone.odb.read_commit(tip)
    assert tip_commit.parents  # the parent oid is still recorded...
    assert not clone.odb.contains(tip_commit.parents[0])  # ...but absent
    assert tip in read_shallow(clone)
    # shallow-tolerant walking: log shows just the tip
    assert len(list(clone.walk_commits(tip))) == 1
    # the tip's data is complete
    assert len(list(clone.datasets("HEAD")[ds_path].features())) == 10


def test_fetch_deepens_shallow_clone(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(
        repo.workdir, tmp_path / "clone", depth=1, do_checkout=False
    )
    tip = clone.head_commit_oid
    assert len(list(clone.walk_commits(tip))) == 1
    transport.fetch(clone, "origin", depth=10)
    # full history now present and the shallow marker is gone
    assert len(list(clone.walk_commits(tip))) == 2
    assert read_shallow(clone) == set()


def test_push_from_shallow_clone_marks_remote_shallow(source_repo, tmp_path):
    repo, ds_path = source_repo
    clone = transport.clone(
        repo.workdir, tmp_path / "clone", depth=1, do_checkout=False
    )
    empty = KartRepo.init_repository(tmp_path / "target", bare=True)
    transport.add_remote(clone, "target", str(tmp_path / "target"))
    transport.push(clone, "target")
    # the truncation is recorded, not silent
    assert clone.head_commit_oid in read_shallow(empty)


def test_clone_into_nonempty_fails_cleanly(source_repo, tmp_path):
    repo, _ = source_repo
    with pytest.raises(RemoteError):
        transport.clone(str(tmp_path / "missing-remote"), tmp_path / "c2")
    assert not (tmp_path / "c2" / ".kart").exists()


def test_remote_management(source_repo, tmp_path):
    repo, _ = source_repo
    other = KartRepo.init_repository(tmp_path / "other")
    transport.add_remote(other, "up", repo.workdir)
    assert other.remotes() == ["up"]
    assert other.remote_url("up") == repo.workdir
    with pytest.raises(RemoteError):
        transport.add_remote(other, "up", "elsewhere")
    transport.remove_remote(other, "up")
    assert other.remotes() == []


class TestSpatialFilteredClone:
    """Filtered partial clone: features outside the filter stay promised
    (reference: kart clone --spatial-filter, SURVEY.md §3.5)."""

    @pytest.fixture()
    def partial_clone(self, source_repo, tmp_path):
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        repo, ds_path = source_repo
        # points are at x=101..110, y=-40.1..-41.0; keep x <= 105.5
        spec = ResolvedSpatialFilterSpec(
            "EPSG:4326",
            "POLYGON((100 -42, 105.5 -42, 105.5 -39, 100 -39, 100 -42))",
        )
        clone = transport.clone(
            repo.workdir,
            tmp_path / "partial",
            spatial_filter_spec=spec,
            do_checkout=False,
        )
        return repo, clone, ds_path

    def test_outside_features_are_promised(self, partial_clone):
        repo, clone, ds_path = partial_clone
        assert clone.config.get_bool("remote.origin.promisor")
        ds = clone.datasets("HEAD")[ds_path]
        # inside-filter feature readable
        f5 = ds.get_feature([5])
        assert f5["name"] == "feature-5"
        # outside-filter feature is promised, not just missing
        with pytest.raises(ObjectPromised):
            ds.get_feature([9])

    def test_promised_blob_fetch_on_demand(self, partial_clone):
        repo, clone, ds_path = partial_clone
        src_ds = repo.datasets("HEAD")[ds_path]
        path = src_ds.encode_1pk_to_path(9, relative=True)  # 'feature/...'
        blob_oid = src_ds.inner_tree.get(path).oid

        fetched = transport.fetch_promised_blobs(clone, [blob_oid])
        assert fetched == 1
        ds = clone.datasets("HEAD")[ds_path]
        assert ds.get_feature([9])["name"] == "feature-9"

    def test_filter_config_written(self, partial_clone):
        _, clone, _ = partial_clone
        assert clone.config.get("kart.spatialfilter.crs") == "EPSG:4326"
        assert "POLYGON" in clone.config.get("kart.spatialfilter.geometry")
        pcf = clone.config.get("remote.origin.partialclonefilter")
        assert pcf and pcf.startswith("extension:spatial=")


def test_cli_clone_push_pull(source_repo, tmp_path, monkeypatch):
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    runner = CliRunner()
    repo, ds_path = source_repo
    clone_dir = tmp_path / "cliclone"
    result = runner.invoke(
        cli, ["clone", "--no-checkout", repo.workdir, str(clone_dir)]
    )
    assert result.exit_code == 0, result.output

    monkeypatch.chdir(clone_dir)
    clone = KartRepo(str(clone_dir))
    clone.config.set_many({"user.name": "X", "user.email": "x@example.com"})
    edit_commit(clone, ds_path, deletes=[7], message="cli edit")
    result = runner.invoke(cli, ["push"])
    assert result.exit_code == 0, result.output
    assert repo.refs.get("refs/heads/main") == clone.head_commit_oid

    # advance source, then pull in the clone (fast-forward)
    new_oid = edit_commit(repo, ds_path, deletes=[8], message="upstream edit")
    result = runner.invoke(cli, ["pull"])
    assert result.exit_code == 0, result.output
    clone = KartRepo(str(clone_dir))
    assert clone.head_commit_oid == new_oid


class TestPromisorBackfill:
    """Readers on a partial clone must handle promised blobs: checkout
    skips out-of-filter features, diff backfills values mid-stream
    (reference: DeltaFetcher, kart/base_diff_writer.py:467-534)."""

    @pytest.fixture()
    def filtered_wc_clone(self, source_repo, tmp_path):
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        repo, ds_path = source_repo
        # points are at x=101..110; keep x <= 105.5
        spec = ResolvedSpatialFilterSpec(
            "EPSG:4326",
            "POLYGON((100 -42, 105.5 -42, 105.5 -39, 100 -39, 100 -42))",
        )
        clone = transport.clone(
            repo.workdir,
            tmp_path / "partial-wc",
            spatial_filter_spec=spec,
            do_checkout=True,
        )
        return repo, clone, ds_path

    def test_checkout_skips_promised_features(self, filtered_wc_clone):
        """The round-1 crash: write_full died on the first promised blob.
        Now the WC materialises exactly the in-filter features."""
        repo, clone, ds_path = filtered_wc_clone
        wc = clone.working_copy
        assert wc is not None
        with wc.session() as con:
            pks = sorted(
                row[0]
                for row in con.execute('SELECT fid FROM "points"').fetchall()
            )
        # fid 1 was updated to a NULL geometry in the second commit (NULL
        # always matches); fids 2..5 are at x=102..105, inside the filter
        assert pks == [1, 2, 3, 4, 5]

    def test_diff_backfills_promised_values(self, filtered_wc_clone, capsys):
        """A committed-range diff buffers deltas whose values are promised,
        batch-fetches their blobs mid-stream, and then applies the clone's
        spatial filter to the fetched values — out-of-filter features stay
        hidden (reference: `kart diff` on a filtered clone shows only
        matching deltas, base_diff_writer.py:279-341 + DeltaFetcher)."""
        import json

        from kart_tpu.diff.writers import BaseDiffWriter

        repo, clone, ds_path = filtered_wc_clone
        src_ds = repo.datasets("HEAD")[ds_path]
        path = src_ds.encode_1pk_to_path(9, relative=True)
        blob_oid = src_ds.inner_tree.get(path).oid
        assert not clone.odb.contains(blob_oid)  # out-of-filter: promised

        writer_cls = BaseDiffWriter.get_diff_writer_class("json")
        writer = writer_cls(clone, "[EMPTY]...HEAD", json_style="compact")
        writer.write_diff()
        out = capsys.readouterr().out
        deltas = json.loads(out)["kart.diff/v1+hexwkb"][ds_path]["feature"]
        inserted_fids = {d["+"]["fid"] for d in deltas if "+" in d}
        # only in-filter deltas stream (fid 1 has a NULL geometry by HEAD:
        # NULL always matches; 2..5 are inside the rect)
        assert inserted_fids == {1, 2, 3, 4, 5}
        # the promised blob WAS backfilled to evaluate the filter exactly
        assert clone.odb.contains(blob_oid)

    def test_diff_shows_everything_when_filter_removed(
        self, filtered_wc_clone, capsys
    ):
        """Clearing the clone's spatial-filter config makes the same diff
        surface every delta — the promised values backfill mid-stream."""
        import json

        from kart_tpu.diff.writers import BaseDiffWriter
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        repo, clone, ds_path = filtered_wc_clone
        spec = ResolvedSpatialFilterSpec.from_repo_config(clone)
        for key in spec.config_items():
            clone.del_config(key)
        writer_cls = BaseDiffWriter.get_diff_writer_class("json")
        writer = writer_cls(clone, "[EMPTY]...HEAD", json_style="compact")
        writer.write_diff()
        out = capsys.readouterr().out
        deltas = json.loads(out)["kart.diff/v1+hexwkb"][ds_path]["feature"]
        inserted_fids = {d["+"]["fid"] for d in deltas if "+" in d}
        assert inserted_fids == set(range(1, 11))

    def test_reset_handles_promised_targets(self, filtered_wc_clone):
        """Branch switching in a filtered clone: deltas whose target values
        are promised are dropped from the WC, not crashed on."""
        from kart_tpu.workingcopy import get_working_copy

        repo, clone, ds_path = filtered_wc_clone
        # move the filtered clone's WC back to the first commit and forward
        # again — both resets cross deltas touching out-of-filter features
        head = clone.head_commit_oid
        parent = clone.structure("HEAD^").commit_oid
        wc = get_working_copy(clone)
        wc.reset(clone.structure(parent))
        clone.refs.set("refs/heads/main", parent, log_message="test rewind")
        with wc.session() as con:
            pks = sorted(
                r[0] for r in con.execute('SELECT fid FROM "points"').fetchall()
            )
        # the WC must hold only in-filter features of HEAD^
        assert 5 in pks and 9 not in pks
        wc.reset(clone.structure(head))
        clone.refs.set("refs/heads/main", head, log_message="test forward")
        with wc.session() as con:
            pks = sorted(
                r[0] for r in con.execute('SELECT fid FROM "points"').fetchall()
            )
        assert pks == [1, 2, 3, 4, 5]

    def test_wc_insert_colliding_with_promised_pk_warns(self, filtered_wc_clone, capsys):
        """Inserting a WC feature whose pk belongs to an out-of-filter
        (promised) feature must surface the reference's spatial-filter pk
        conflict warning (kart/commit.py:40-74), not a silent insert."""
        from kart_tpu.diff.writers import BaseDiffWriter

        repo, clone, ds_path = filtered_wc_clone
        wc = clone.working_copy
        with wc.session() as con:
            con.execute(
                'INSERT INTO "points" (fid, name, rating, geom) '
                "VALUES (9, 'collider', 1.0, NULL)"
            )
        writer_cls = BaseDiffWriter.get_diff_writer_class("text")
        writer = writer_cls(clone, "HEAD")
        writer.write_diff()
        err = capsys.readouterr().err
        assert "outside the spatial filter" in err
        assert writer.spatial_filter_pk_conflicts.get(ds_path) == [9]


def test_fetch_skips_invalid_remote_ref_names(source_repo, tmp_path, capsys):
    """A hostile/buggy remote exposing refs git's check_refname_format
    rejects ('x.lock', '.hidden') must not get those names planted under
    refs/remotes/ — they are skipped with a warning while good refs still
    fetch (same rules the receive-pack side enforces)."""
    repo, ds_path = source_repo
    clone = transport.clone(repo.workdir, tmp_path / "clone", do_checkout=False)
    # Plant hostile ref files directly in the remote's gitdir (refs.set
    # would itself reject some of these shapes).
    oid = repo.head_commit_oid
    for bad in ("evil.lock", ".hidden"):
        with open(os.path.join(repo.gitdir, "refs", "heads", bad), "w") as f:
            f.write(oid + "\n")
    new_oid = edit_commit(repo, ds_path, deletes=[2], message="advance")
    updated = transport.fetch(clone, "origin")
    assert updated.get("refs/remotes/origin/main") == new_oid
    assert clone.refs.get("refs/remotes/origin/evil.lock") is None
    assert clone.refs.get("refs/remotes/origin/.hidden") is None
    assert not os.path.exists(
        os.path.join(clone.gitdir, "refs", "remotes", "origin", "evil.lock")
    )
    assert "invalid remote ref name" in capsys.readouterr().err


def test_checkout_guess_remote_branch(source_repo, tmp_path):
    """Checking out a bare name that only exists as a remote branch creates
    a local tracking branch (reference: kart checkout --guess default)."""
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    repo, ds_path = source_repo
    # a branch on the source beyond main
    repo.refs.set(
        "refs/heads/feature-x", repo.head_commit_oid, "branch: for guess test"
    )
    clone = transport.clone(repo.workdir, tmp_path / "guess-clone", do_checkout=False)
    assert not clone.refs.exists("refs/heads/feature-x")
    runner = CliRunner()
    r = runner.invoke(
        cli, ["-C", str(tmp_path / "guess-clone"), "checkout", "feature-x"]
    )
    assert r.exit_code == 0, r.output
    assert "tracking" in r.output
    clone2 = KartRepo(str(tmp_path / "guess-clone"))
    assert clone2.refs.exists("refs/heads/feature-x")
    assert clone2.head_branch == "refs/heads/feature-x"
    assert clone2.config.get("branch.feature-x.remote") == "origin"
