"""Spatial filter: spec parsing, per-dataset matching, envelope index
(reference: tests/test_spatial_filter.py + test_spatial_filter_index.py)."""

import pytest

from kart_tpu.spatial_filter import (
    MatchResult,
    ResolvedSpatialFilterSpec,
    SpatialFilter,
    SpatialFilterError,
    _rect_overlaps,
)
from kart_tpu.spatial_filter.index import (
    EnvelopeIndexReader,
    update_spatial_filter_index,
)

from conftest import extract_ref_archive, needs_ref_fixtures
from helpers import edit_commit, make_imported_repo

POLY_100_105 = "POLYGON((100 -42, 105.5 -42, 105.5 -39, 100 -39, 100 -42))"


class TestSpecParsing:
    def test_crs_and_wkt(self):
        spec = ResolvedSpatialFilterSpec.from_spec_string(
            f"EPSG:4326;{POLY_100_105}"
        )
        assert not spec.match_all
        w, s, e, n = spec.envelope_wsen_4326
        assert (w, s, e, n) == (100.0, -42.0, 105.5, -39.0)
        assert spec.filter_arg.startswith("100.0000000,-42.0000000,")

    def test_from_file(self, tmp_path):
        f = tmp_path / "filter.txt"
        f.write_text(f"EPSG:4326;{POLY_100_105}")
        spec = ResolvedSpatialFilterSpec.from_spec_string(f"@{f}")
        assert spec.envelope_wsen_4326[0] == 100.0

    def test_none_is_match_all(self):
        assert ResolvedSpatialFilterSpec.from_spec_string("none").match_all
        assert ResolvedSpatialFilterSpec.from_spec_string("").match_all

    def test_bad_spec(self):
        with pytest.raises(SpatialFilterError):
            ResolvedSpatialFilterSpec.from_spec_string("no-semicolon-here")

    def test_non_polygon_rejected(self):
        from kart_tpu.geometry import GeometryError

        with pytest.raises(GeometryError):
            ResolvedSpatialFilterSpec.from_spec_string("EPSG:4326;POINT(1 2)")

    def test_config_items_roundtrip(self):
        spec = ResolvedSpatialFilterSpec.from_spec_string(
            f"EPSG:4326;{POLY_100_105}"
        )
        items = spec.config_items()
        assert items["kart.spatialfilter.crs"] == "EPSG:4326"
        assert "POLYGON" in items["kart.spatialfilter.geometry"]


class TestRectOverlaps:
    def test_basic(self):
        # env: (min-x, max-x, min-y, max-y); rect: (w, e, s, n)
        assert _rect_overlaps((0, 10, 0, 10), (5, 15, 5, 15))
        assert not _rect_overlaps((0, 10, 0, 10), (11, 15, 0, 10))
        assert not _rect_overlaps((0, 10, 0, 10), (0, 10, 11, 15))

    def test_antimeridian_rect(self):
        # rect from 170 to -170 crossing the anti-meridian
        assert _rect_overlaps((175, 176, 0, 1), (170, -170, -5, 5))
        assert _rect_overlaps((-176, -175, 0, 1), (170, -170, -5, 5))
        assert not _rect_overlaps((0, 10, 0, 1), (170, -170, -5, 5))

    def test_antimeridian_env(self):
        assert _rect_overlaps((170, -170, 0, 1), (160, 175, -5, 5))
        assert _rect_overlaps((170, -170, 0, 1), (-175, -160, -5, 5))


class TestDatasetFilter:
    @pytest.fixture()
    def repo_ds(self, tmp_path):
        repo, ds_path = make_imported_repo(tmp_path, n=10)
        return repo, repo.datasets("HEAD")[ds_path]

    def test_matches_features(self, repo_ds):
        repo, ds = repo_ds
        spec = ResolvedSpatialFilterSpec("EPSG:4326", POLY_100_105)
        sf = spec.resolve_for_dataset(ds)
        assert sf  # not match-all
        # points are at x = 100 + fid
        assert sf.match_result(ds.get_feature([3])) is MatchResult.MATCHED
        assert sf.match_result(ds.get_feature([9])) is MatchResult.NOT_MATCHED

    def test_null_geometry_matches(self, repo_ds):
        _, ds = repo_ds
        spec = ResolvedSpatialFilterSpec("EPSG:4326", POLY_100_105)
        sf = spec.resolve_for_dataset(ds)
        feature = dict(ds.get_feature([9]))
        feature["geom"] = None
        assert sf.match_result(feature) is MatchResult.MATCHED

    def test_match_all_spec(self, repo_ds):
        _, ds = repo_ds
        spec = ResolvedSpatialFilterSpec(None, None, match_all=True)
        assert spec.resolve_for_dataset(ds) is SpatialFilter.MATCH_ALL

    def test_polygon_exactness(self, repo_ds):
        """A feature inside the filter's bbox but outside the polygon itself
        is excluded (the triangle covers the bbox's lower-left half)."""
        _, ds = repo_ds
        triangle = "POLYGON((100 -42, 106 -42, 100 -39, 100 -42))"
        spec = ResolvedSpatialFilterSpec("EPSG:4326", triangle)
        sf = spec.resolve_for_dataset(ds)
        # fid=1 at (101, -40.1): inside triangle (left edge region)
        assert sf.match_result(ds.get_feature([1])) is MatchResult.MATCHED
        # fid=5 at (105, -40.5): inside bbox, outside the hypotenuse
        assert sf.match_result(ds.get_feature([5])) is MatchResult.NOT_MATCHED

    def test_polygon_with_hole(self, repo_ds):
        """A feature inside an interior ring (hole) of the filter polygon
        does not match; features in the solid annulus do. Points sit at
        (100+fid, -40-fid/10)."""
        _, ds = repo_ds
        holed = (
            "POLYGON((100 -45, 106 -45, 106 -39, 100 -39, 100 -45),"
            "(102 -41, 104 -41, 104 -40, 102 -40, 102 -41))"
        )
        spec = ResolvedSpatialFilterSpec("EPSG:4326", holed)
        sf = spec.resolve_for_dataset(ds)
        # fid=3 at (103, -40.3): inside the hole -> excluded
        assert sf.match_result(ds.get_feature([3])) is MatchResult.NOT_MATCHED
        # fid=5 at (105, -40.5): inside outer, outside the hole -> matched
        assert sf.match_result(ds.get_feature([5])) is MatchResult.MATCHED

    def test_multipolygon_all_parts(self, repo_ds):
        """Every part of a MultiPolygon filter matches features — not just
        the first part (the round-1 approximation)."""
        _, ds = repo_ds
        multi = (
            "MULTIPOLYGON(((100.5 -41, 101.5 -41, 101.5 -40, 100.5 -40, 100.5 -41)),"
            "((104.5 -41, 105.5 -41, 105.5 -40, 104.5 -40, 104.5 -41)))"
        )
        spec = ResolvedSpatialFilterSpec("EPSG:4326", multi)
        sf = spec.resolve_for_dataset(ds)
        # fid=1 at (101, -40.1): inside part 1
        assert sf.match_result(ds.get_feature([1])) is MatchResult.MATCHED
        # fid=5 at (105, -40.5): inside part 2 (second part must count)
        assert sf.match_result(ds.get_feature([5])) is MatchResult.MATCHED
        # fid=3 at (103, -40.3): between the parts, inside neither
        assert sf.match_result(ds.get_feature([3])) is MatchResult.NOT_MATCHED

    def test_line_envelope_overlap_geometry_disjoint(self):
        """VERDICT r2 missing #2: a diagonal line whose ENVELOPE clips the
        filter rect but whose geometry stays clear must be NOT_MATCHED —
        GEOS Intersects semantics on the real geometry, not the envelope
        (reference: kart/spatial_filter/__init__.py:556-590)."""
        import struct

        from kart_tpu.geometry import Geometry
        from kart_tpu.spatial_filter import MatchResult, SpatialFilter

        def line_geom(coords):
            wkb = struct.pack("<BII", 1, 2, len(coords)) + b"".join(
                struct.pack("<2d", *c) for c in coords
            )
            return Geometry.from_wkb(wkb)

        # rect x:[6,10] y:[0,4]; the line y=x misses it entirely
        sf = SpatialFilter((6, 10, 0, 4), "geom")
        diagonal = {"geom": line_geom([(0, 0), (10, 10)])}
        assert sf.match_result(diagonal) is MatchResult.NOT_MATCHED
        crossing = {"geom": line_geom([(0, 0), (10, 2)])}
        assert sf.match_result(crossing) is MatchResult.MATCHED
        inside = {"geom": line_geom([(7, 1), (9, 3)])}
        assert sf.match_result(inside) is MatchResult.MATCHED

    def test_polygon_feature_envelope_overlap_geometry_disjoint(self):
        """An L-shaped feature polygon whose envelope overlaps the filter
        rect but whose area doesn't: excluded; and mutual-containment cases
        still intersect."""
        import struct

        from kart_tpu.geometry import Geometry
        from kart_tpu.spatial_filter import MatchResult, SpatialFilter

        def poly_geom(*rings):
            wkb = struct.pack("<BII", 1, 3, len(rings))
            for ring in rings:
                wkb += struct.pack("<I", len(ring)) + b"".join(
                    struct.pack("<2d", *c) for c in ring
                )
            return Geometry.from_wkb(wkb)

        # L-shape occupying the left column + bottom row of its bbox [0,10]^2
        L_shape = poly_geom(
            [(0, 0), (10, 0), (10, 2), (2, 2), (2, 10), (0, 10), (0, 0)]
        )
        # filter rect in the bbox's upper-right: envelope hits, geometry doesn't
        sf = SpatialFilter((5, 9, 5, 9), "geom")
        assert sf.match_result({"geom": L_shape}) is MatchResult.NOT_MATCHED
        # filter rect overlapping the bottom arm: matched
        sf2 = SpatialFilter((5, 9, 1, 9), "geom")
        assert sf2.match_result({"geom": L_shape}) is MatchResult.MATCHED
        # feature polygon CONTAINING the filter: no boundary crossing, still
        # intersects (filter corner inside feature)
        big = poly_geom([(-100, -100), (100, -100), (100, 100), (-100, 100), (-100, -100)])
        assert sf.match_result({"geom": big}) is MatchResult.MATCHED
        # feature wholly inside a hole of the feature... and the hole case:
        # filter inside the feature's hole -> disjoint
        donut = poly_geom(
            [(-100, -100), (100, -100), (100, 100), (-100, 100), (-100, -100)],
            [(-50, -50), (50, -50), (50, 50), (-50, 50), (-50, -50)],
        )
        assert sf.match_result({"geom": donut}) is MatchResult.NOT_MATCHED

    def test_polygon_filter_exact_residue_on_line(self, repo_ds):
        """Triangle filter + a line feature cutting only through the
        triangle-free half of the filter bbox: excluded."""
        import struct

        from kart_tpu.geometry import Geometry
        from kart_tpu.spatial_filter import MatchResult, ResolvedSpatialFilterSpec

        _, ds = repo_ds
        # lower-left triangle of bbox (100..106, -42..-39)
        triangle = "POLYGON((100 -42, 106 -42, 100 -39, 100 -42))"
        spec = ResolvedSpatialFilterSpec("EPSG:4326", triangle)
        sf = spec.resolve_for_dataset(ds)

        def line_geom(coords):
            wkb = struct.pack("<BII", 1, 2, len(coords)) + b"".join(
                struct.pack("<2d", *c) for c in coords
            )
            return Geometry.from_wkb(wkb)

        # hugs the bbox's top-right corner, entirely above the hypotenuse
        outside = {"geom": line_geom([(105.9, -39.05), (105.99, -39.4)])}
        assert sf.match_result(outside) is MatchResult.NOT_MATCHED
        # crosses the hypotenuse
        through = {"geom": line_geom([(100.5, -41.5), (105.5, -39.2)])}
        assert sf.match_result(through) is MatchResult.MATCHED

    def test_unknown_crs_fails_open_with_warning(self, repo_ds, caplog):
        """A filter that can't be transformed into the dataset CRS must warn
        and match everything, never silently drop features."""
        import logging

        _, ds = repo_ds
        unknown = (
            'PROJCS["mystery",GEOGCS["WGS 84",DATUM["WGS_1984",'
            'SPHEROID["WGS 84",6378137,298.257223563]],PRIMEM["Greenwich",0],'
            'UNIT["degree",0.0174532925199433]],'
            'PROJECTION["New_Zealand_Map_Grid"],'
            'PARAMETER["latitude_of_origin",-41],PARAMETER["central_meridian",173],'
            'UNIT["metre",1]]'
        )
        spec = ResolvedSpatialFilterSpec(
            unknown, "POLYGON((0 0, 1000 0, 1000 1000, 0 1000, 0 0))"
        )
        with caplog.at_level(logging.WARNING, "kart_tpu.spatial_filter"):
            sf = spec.resolve_for_dataset(ds)
        assert sf is SpatialFilter.MATCH_ALL
        assert any(
            "cannot be transformed" in rec.message for rec in caplog.records
        )


class TestEnvelopeIndex:
    def test_build_and_lookup(self, tmp_path):
        repo, ds_path = make_imported_repo(tmp_path, n=10)
        n_features, n_commits = update_spatial_filter_index(repo)
        assert n_commits == 1
        assert n_features == 10

        reader = EnvelopeIndexReader.open(repo)
        assert reader is not None
        assert reader.count() == 10

        ds = repo.datasets("HEAD")[ds_path]
        path = ds.encode_1pk_to_path(4, relative=True)  # 'feature/...'
        oid = ds.inner_tree.get(path).oid
        env = reader.get(oid)
        assert env is not None
        w, s, e, n = env
        # point at (104, -40.4); stored envelope contains it with <1e-3 slack
        assert w <= 104.0 <= e and s <= -40.4 <= n
        assert e - w < 0.01 and n - s < 0.01

    def test_incremental(self, tmp_path):
        repo, ds_path = make_imported_repo(tmp_path, n=10)
        update_spatial_filter_index(repo)
        edit_commit(
            repo,
            ds_path,
            inserts=[
                {
                    "fid": 11,
                    "geom": None,
                    "name": "no-geom",
                    "rating": 0.0,
                }
            ],
            message="insert",
        )
        n_features, n_commits = update_spatial_filter_index(repo)
        assert n_commits == 1  # only the new commit
        # the new feature has no geometry -> nothing new to index
        assert n_features == 0
        # re-run: fully up to date
        assert update_spatial_filter_index(repo) == (0, 0)

    def test_all_envelopes_batch(self, tmp_path):
        repo, _ = make_imported_repo(tmp_path, n=10)
        update_spatial_filter_index(repo)
        reader = EnvelopeIndexReader.open(repo)
        oids, wsen = reader.all_envelopes()
        assert len(oids) == 10
        assert wsen.shape == (10, 4)
        # all points are within x 101..110, y -41..-40.1
        assert wsen[:, 0].min() >= 100.9 and wsen[:, 2].max() <= 110.1


def test_cli_spatial_filter_commands(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    repo, _ = make_imported_repo(tmp_path, n=10)
    monkeypatch.chdir(repo.workdir)
    runner = CliRunner()
    r = runner.invoke(cli, ["spatial-filter", "index"])
    assert r.exit_code == 0, r.output
    assert "Indexed 10 feature envelopes" in r.output

    r = runner.invoke(
        cli, ["spatial-filter", "resolve", f"EPSG:4326;{POLY_100_105}"]
    )
    assert r.exit_code == 0, r.output
    assert "100.0000000,-42.0000000,105.5000000,-39.0000000" in r.output


@needs_ref_fixtures
def test_reference_built_envelope_index_interop(tmp_path):
    """The reference's own prebuilt feature_envelopes.db (from its
    polygons-with-feature-envelopes fixture) opens directly: same table
    name, same 20-bit envelope codec, and the incremental indexer
    recognises its commits anchor as up to date."""
    src = extract_ref_archive(
        tmp_path, "polygons-with-feature-envelopes.tgz"
    )
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.crs import CRS, Transform
    from kart_tpu.spatial_filter import EPSG_4326_WKT
    from kart_tpu.spatial_filter.index import (
        EnvelopeIndexReader,
        update_spatial_filter_index,
    )

    repo = KartRepo(src)
    reader = EnvelopeIndexReader.open(repo)
    assert reader is not None
    oids, wsen = reader.all_envelopes()
    assert len(oids) == 228
    idx = dict(zip(oids, wsen))

    (ds,) = list(repo.datasets("HEAD"))
    crs_wkt = ds.get_crs_definition(ds.crs_identifiers()[0])
    t = Transform(CRS(crs_wkt), EPSG_4326_WKT)
    checked = 0
    for path, entry in ds.feature_tree.walk_blobs():
        if entry.oid not in idx:
            continue
        geom = ds.get_feature(path=path)[ds.geom_column_name]
        if geom is None:
            continue
        x0, x1, y0, y1 = t.transform_envelope(geom.envelope())
        w, s, e, n = idx[entry.oid]
        # codec rounds outward (+ curvature buffer): reference envelopes
        # must contain our recomputed ones
        assert w <= x0 + 1e-3 and e >= x1 - 1e-3
        assert s <= y0 + 1e-3 and n >= y1 - 1e-3
        checked += 1
        if checked >= 25:
            break
    assert checked == 25

    n_feat, n_commits = update_spatial_filter_index(repo)
    assert (n_feat, n_commits) == (0, 0)  # anchor recognised, no re-index


def test_legacy_blobs_table_migrates(tmp_path):
    """Early builds named the envelope table 'blobs'; opening or updating
    such an index renames it instead of silently abandoning the data."""
    import sqlite3

    repo, ds_path = make_imported_repo(tmp_path, n=5)
    n_feat, _ = update_spatial_filter_index(repo)
    assert n_feat == 5
    from kart_tpu.spatial_filter.index import db_path

    con = sqlite3.connect(db_path(repo))
    con.execute("ALTER TABLE feature_envelopes RENAME TO blobs")
    con.commit()
    con.close()

    reader = EnvelopeIndexReader.open(repo)
    assert reader is not None and reader.count() == 5
    assert update_spatial_filter_index(repo) == (0, 0)  # still up to date


@needs_ref_fixtures
@pytest.mark.parametrize("rel", ["antimeridian-3832.tgz", "antimeridian-3994.tgz"])
def test_antimeridian_fixture_envelope_index(tmp_path, rel):
    """The reference's Pacific fixtures (PDC Mercator 3832 / 2SP Mercator
    3994) index with correct longitudes: features near the date line land
    at ±180, and envelopes straddling it are stored cyclically (w > e) —
    not clamped."""
    import numpy as np

    src = extract_ref_archive(tmp_path, rel)
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(src)
    n_feat, _ = update_spatial_filter_index(repo)
    assert n_feat == 616
    reader = EnvelopeIndexReader.open(repo)
    oids, wsen = reader.all_envelopes()
    lons = np.concatenate([wsen[:, 0], wsen[:, 2]])
    assert lons.min() >= -180.0 and lons.max() <= 180.0
    assert abs(lons).max() > 160.0  # Pacific data, near the date line
    crossing = wsen[wsen[:, 0] > wsen[:, 2]]
    assert len(crossing) == 2

    # a query rect crossing the anti-meridian finds the crossing features
    from kart_tpu.native import bbox_intersects

    hits = bbox_intersects(wsen, (179.0, -60.0, -179.0, -45.0))
    assert hits.sum() >= 2


def test_world_spanning_envelope_not_indexed():
    """A transformed envelope whose longitude span reaches >= 180 deg is
    ambiguous after endpoint-wise wrapping (e.g. EPSG:3832 lon -30..330
    wraps to a sliver) — the indexer must skip it so the blob fails open on
    filtered clones, matching the reference's transform_minmax_envelope
    giving up (reference kart/spatial_filter/index.py:639+)."""
    import sqlite3

    from kart_tpu.ops.envelope_codec import EnvelopeCodec
    from kart_tpu.spatial_filter.index import _BatchedEnvelopeExtractor, _SCHEMA

    con = sqlite3.connect(":memory:")
    con.executescript(_SCHEMA)
    extractor = _BatchedEnvelopeExtractor.__new__(_BatchedEnvelopeExtractor)
    extractor.codec = EnvelopeCodec()
    bucket = [
        (b"\x01" * 20, (-30.0, 330.0, -10.0, 10.0)),  # world-spanning: skip
        (b"\x02" * 20, (10.0, 20.0, -10.0, 10.0)),  # normal: keep
        (b"\x03" * 20, (float("nan"), 20.0, -10.0, 10.0)),  # NaN w: skip
        (b"\x04" * 20, (10.0, 20.0, -10.0, float("nan"))),  # NaN n: skip
        (b"\x05" * 20, (float("nan"),) * 4),  # all-NaN: skip
        (b"\x06" * 20, (150.0, 200.0, -10.0, 10.0)),  # antimeridian: keep
    ]
    # One bad row must not abort the whole bucket (codec raises on NaN).
    extractor._flush_bucket(con, None, bucket)
    rows = {r[0] for r in con.execute("SELECT blob_id FROM feature_envelopes")}
    assert rows == {b"\x02" * 20, b"\x06" * 20}


class TestMixedGeometryBoundaryTouch:
    def test_collection_point_on_filter_edge_matches(self):
        """GEOS Intersects counts a boundary touch; a feature whose point
        lies exactly on the filter edge must match even when the feature
        also has disjoint lines/polygons (ADVICE r3: the touch test used to
        run only for points-only features)."""
        import numpy as np

        from kart_tpu.spatial_filter import _geom_intersects_polygon_set

        square = np.array(
            [(0.0, 0.0), (10.0, 0.0), (10.0, 10.0), (0.0, 10.0), (0.0, 0.0)]
        )
        parts = [(square, [])]
        feat = {
            "points": np.array([[5.0, 0.0]]),  # exactly on the bottom edge
            "lines": [np.array([[20.0, 20.0], [30.0, 30.0]])],  # disjoint
            "polys": [],
        }
        assert _geom_intersects_polygon_set(feat, parts)
        # and a disjoint point with disjoint lines stays unmatched
        feat_out = {
            "points": np.array([[50.0, 50.0]]),
            "lines": [np.array([[20.0, 20.0], [30.0, 30.0]])],
            "polys": [],
        }
        assert not _geom_intersects_polygon_set(feat_out, parts)


def test_spatial_filter_spec_with_registry_epsg_code():
    """A filter spec whose CRS is a bare registry EPSG code (not in the
    curated _WELL_KNOWN WKTs) resolves through kart_tpu/epsg.py: the
    polygon is given in OSGB eastings/northings and must reproject to a
    lon/lat envelope near Greenwich."""
    spec = ResolvedSpatialFilterSpec.from_spec_string(
        "EPSG:27700;POLYGON((530000 180000, 532000 180000, "
        "532000 182000, 530000 182000, 530000 180000))"
    )
    w, s, e, n = spec.envelope_wsen_4326
    assert -0.3 < w < e < 0.1  # around Greenwich
    assert 51.4 < s < n < 51.7
