"""Block-pruned spatial diffs (ISSUE 1 tentpole): the sidecar's per-block
envelope aggregates must make the pruned scan bit-identical to the full
branchless f32 residue scan — including anti-meridian-wrap members,
wrapping queries, degenerate envelopes, and boundary-straddling blocks —
and pre-aggregate (old-format) sidecars must keep diffing correctly via
the full-scan fallback.
"""

import io
import json

import numpy as np
import pytest

from kart_tpu import native
from kart_tpu.diff.sidecar import _block_aggregates
from kart_tpu.ops.bbox import bbox_blocks_np, bbox_intersects_np


def _random_envelopes(rng, n, *, wrap_frac=0.02, full_frac=0.01,
                      degen_frac=0.005, nonfinite_frac=0.005):
    env = np.empty((n, 4), np.float32)
    env[:, 0] = rng.uniform(-180, 180, n)
    env[:, 1] = rng.uniform(-90, 89, n)
    env[:, 2] = env[:, 0] + rng.uniform(0, 3, n).astype(np.float32)
    env[:, 3] = env[:, 1] + rng.uniform(0, 3, n).astype(np.float32)
    # anti-meridian wrap: e < w, with both ends in-domain (needs w > -180)
    wrap = (rng.random(n) < wrap_frac) & (env[:, 0] > -170)
    env[wrap, 2] = rng.uniform(-180, env[wrap, 0] - 1).astype(np.float32)
    full = rng.random(n) < full_frac  # NULL-geometry fail-open envelopes
    env[full] = (-180, -90, 180, 90)
    degen = rng.random(n) < degen_frac  # inverted lat: matches nothing
    env[degen, 3] = env[degen, 1] - 1.0
    # corrupt-geometry envelopes: a NaN field must not poison its block's
    # aggregate into silent all-out drops of its neighbours
    kind = rng.integers(0, 4, n)
    for k, v in enumerate((np.nan, np.inf, -np.inf)):
        sel = (rng.random(n) < nonfinite_frac) & (kind == k)
        env[sel, rng.integers(0, 4)] = v
    return env


QUERIES = [
    (-40.0, -20.0, -4.0, -3.0),  # region (the bench filter)
    (170.0, -10.0, -170.0, 10.0),  # wrapping query across the anti-meridian
    (-180.0, -90.0, 180.0, 90.0),  # whole world (every non-degenerate row)
    (0.0, 0.0, 0.0, 0.0),  # degenerate point query
    (-180.0001, -90.0, 180.0001, 90.0),  # padded past the lon range
    (100.0, -42.0, 106.0, -39.0),  # small box
]


@pytest.mark.parametrize("block_rows", [7, 64, 4096])
def test_pruned_scan_parity_fuzz(block_rows):
    """Each engine's block-pruned scan is bit-identical to its own unpruned
    scan for randomized envelope sets and query shapes — the contract the
    filtered diff relies on (the engine uses one scan implementation
    consistently). On NaN-free rows both engines also agree with each
    other; NaN-field rows are where the f32 and f64 formulas legitimately
    differ, which is why NaN members force their block to boundary."""
    rng = np.random.default_rng(block_rows)
    env = _random_envelopes(rng, 20_000)
    agg, flags = _block_aggregates(env, block_rows)
    # non-finite fields are where the f32 and f64 formulas legitimately
    # disagree (pre-existing, full scans included) — the cross-engine
    # agreement claim only holds for finite rows
    finite_rows = np.isfinite(env).all(axis=1)
    for q in QUERIES:
        q = np.asarray(q, np.float64)
        with np.errstate(invalid="ignore"):
            ref_np = bbox_intersects_np(env.astype(np.float64), q)
            got_np = bbox_blocks_np(env, agg, flags, block_rows, q)
        assert (got_np == ref_np).all(), q
        ref_f32 = np.asarray(native.bbox_intersects_f32(env, q))
        got_native = np.asarray(
            native.bbox_blocks_f32(env, agg, flags, block_rows, q)
        )
        assert (got_native == ref_f32).all(), q
        # cross-engine agreement wherever no field is NaN
        assert (got_native[finite_rows] == ref_np[finite_rows]).all(), q


def test_aggregates_are_supersets():
    """Every member envelope's lat range is inside its block aggregate, and
    unflagged blocks' lon ranges too (the all-in/all-out soundness basis).
    NaN members are excluded from the union (they can never match a query)
    but must flag the block; any non-finite member flags it too."""
    rng = np.random.default_rng(7)
    env = _random_envelopes(rng, 5_000)
    block_rows = 32
    agg, flags = _block_aggregates(env, block_rows)
    for b in range(len(agg)):
        lo, hi = b * block_rows, min((b + 1) * block_rows, len(env))
        sl = env[lo:hi]
        ok = ~np.isnan(sl).any(axis=1)
        if ok.any():
            assert agg[b, 1] <= sl[ok, 1].min()
            assert agg[b, 3] >= sl[ok, 3].max()
        wraps = sl[:, 2] < sl[:, 0]
        degen = sl[:, 3] < sl[:, 1]
        nonfin = ~np.isfinite(sl).all(axis=1)
        if wraps.any() or degen.any() or nonfin.any():
            assert flags[b] == 1
        else:
            assert flags[b] == 0
            assert agg[b, 0] <= sl[:, 0].min()
            assert agg[b, 2] >= sl[:, 2].max()


class TestEndToEnd:
    """Filtered diffs through the real engine + writers: pruned output must
    be byte-identical to unpruned, and old-format sidecars must fall back."""

    FILTER = (
        "EPSG:4326;POLYGON((-60 -40, 30 -40, 30 20, -60 20, -60 -40))"
    )

    @pytest.fixture(scope="class")
    def spatial_repo(self, tmp_path_factory):
        from kart_tpu.diff import sidecar
        from kart_tpu.synth import synth_repo

        # small aggregate blocks so a 20k-row repo exercises many blocks
        orig = sidecar.AGG_BLOCK_ROWS
        sidecar.AGG_BLOCK_ROWS = 256
        try:
            path = tmp_path_factory.mktemp("prune") / "repo"
            repo, info = synth_repo(
                str(path), 20_000, edit_frac=0.05, spatial=True,
                blobs="changed",
            )
        finally:
            sidecar.AGG_BLOCK_ROWS = orig
        return repo, info

    def _set_filter(self, repo):
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        spec = ResolvedSpatialFilterSpec.from_spec_string(self.FILTER)
        repo.config.set_many(spec.config_items())
        return spec

    def _clear_filter(self, repo, spec):
        for key in spec.config_items():
            repo.del_config(key)

    def _jsonl(self, repo):
        from kart_tpu.diff.writers import JsonLinesDiffWriter

        out = io.StringIO()
        JsonLinesDiffWriter(repo, "HEAD^...HEAD", output_path=out).write_diff()
        return out.getvalue()

    def test_sidecar_has_aggregates(self, spatial_repo):
        from kart_tpu.diff import sidecar

        repo, _ = spatial_repo
        ds = repo.structure("HEAD").datasets["synth"]
        block = sidecar.load_block(repo, ds)
        assert block.env_blocks is not None
        agg, flags, block_rows = block.env_blocks
        assert block_rows == 256
        assert len(agg) == -(-block.count // 256)
        assert len(flags) == len(agg)

    def test_pruned_output_byte_identical(self, spatial_repo, monkeypatch):
        repo, info = spatial_repo
        spec = self._set_filter(repo)
        try:
            pruned = self._jsonl(repo)
            monkeypatch.setenv("KART_BLOCK_PRUNE", "0")
            unpruned = self._jsonl(repo)
        finally:
            self._clear_filter(repo, spec)
        assert pruned == unpruned
        # the filter covers (90/360)*(60/170) ~ 9% of the layer: real rows
        # must stream, but far fewer than the whole changed set
        n_lines = pruned.count("\n")
        assert 1 < n_lines - 1 < info["n_edits"]

    def test_filtered_count_matches_unpruned(self, spatial_repo, monkeypatch):
        from kart_tpu.diff.engine import get_dataset_feature_count_fast
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        repo, _ = spatial_repo
        spec = ResolvedSpatialFilterSpec.from_spec_string(self.FILTER)
        base_rs = repo.structure("HEAD^")
        target_rs = repo.structure("HEAD")
        pruned = get_dataset_feature_count_fast(
            base_rs, target_rs, "synth", spatial_filter_spec=spec
        )
        monkeypatch.setenv("KART_BLOCK_PRUNE", "0")
        unpruned = get_dataset_feature_count_fast(
            base_rs, target_rs, "synth", spatial_filter_spec=spec
        )
        assert pruned == unpruned
        assert pruned > 0

    def test_old_format_sidecar_falls_back(self, spatial_repo, tmp_path):
        """Sidecars written without aggregate records (the pre-ISSUE-1
        format) still produce correct filtered diffs via the full scan."""
        import numpy as np

        from kart_tpu.diff import sidecar
        from kart_tpu.synth import synth_envelopes

        repo, info = spatial_repo
        spec = self._set_filter(repo)
        try:
            with_agg = self._jsonl(repo)

            # rewrite both sidecars in the old format (no aggregates)
            base = 1 << 24
            pks = np.arange(base, base + info["n"], dtype=np.int64)
            envs = synth_envelopes(pks)
            orig = sidecar.AGG_BLOCK_ROWS
            sidecar.AGG_BLOCK_ROWS = 0
            try:
                for rev in ("HEAD^", "HEAD"):
                    ds = repo.structure(rev).datasets["synth"]
                    block = sidecar.load_block(repo, ds, pad=False)
                    oids_u8 = (
                        np.ascontiguousarray(block.oids)
                        .view(np.uint8)
                        .reshape(-1, 20)
                    )
                    sidecar.save_sidecar(
                        repo, ds.feature_tree.oid, np.asarray(block.keys),
                        oids_u8, envelopes=envs,
                    )
                    reloaded = sidecar.load_block(repo, ds)
                    assert reloaded.env_blocks is None  # old format
                    assert reloaded.envelopes is not None
            finally:
                sidecar.AGG_BLOCK_ROWS = orig
            without_agg = self._jsonl(repo)
        finally:
            self._clear_filter(repo, spec)
        assert with_agg == without_agg

        # restore aggregate-carrying sidecars for other tests in the class
        from kart_tpu.diff.sidecar import save_sidecar

        orig = sidecar.AGG_BLOCK_ROWS
        sidecar.AGG_BLOCK_ROWS = 256
        try:
            for rev in ("HEAD^", "HEAD"):
                ds = repo.structure(rev).datasets["synth"]
                block = sidecar.load_block(repo, ds, pad=False)
                oids_u8 = (
                    np.ascontiguousarray(block.oids).view(np.uint8).reshape(-1, 20)
                )
                save_sidecar(
                    repo, ds.feature_tree.oid, np.asarray(block.keys),
                    oids_u8, envelopes=envs,
                )
        finally:
            sidecar.AGG_BLOCK_ROWS = orig


def test_bbox_blocks_shape_mismatch_rejected():
    """The native entry point refuses inconsistent (n, nb, block_rows)."""
    env = np.zeros((10, 4), np.float32)
    agg, flags = _block_aggregates(env, 4)
    lib = native.load()
    if lib is None or not hasattr(lib, "sf_bbox_blocks_f32"):
        pytest.skip("native lib unavailable")
    out = np.empty(10, np.uint8)
    q = np.zeros(4, np.float64)
    rc = lib.sf_bbox_blocks_f32(
        np.ascontiguousarray(env).ctypes.data, 10,
        np.ascontiguousarray(agg).ctypes.data, flags.ctypes.data,
        len(agg) + 1, 4, q.ctypes.data, out.ctypes.data,
    )
    assert rc == -1
