"""Packfile machinery: writer/reader roundtrip, delta resolution against
hand-assembled packs, packed-refs, and the reference fixture repos as
known-answer oracles (SURVEY.md §7 step 1: "reference repos are readable
test oracles")."""

import hashlib
import os
import struct
import tarfile
import zlib

import pytest

from kart_tpu.core.odb import ObjectDb
from kart_tpu.core.packs import (
    OBJ_BLOB,
    PackCollection,
    Packfile,
    PackWriter,
    apply_delta,
    write_pack_index,
)
from kart_tpu.core.refs import RefStore


def _obj_sha(obj_type, content):
    return hashlib.sha1(
        b"%s %d\x00" % (obj_type.encode(), len(content)) + content
    ).digest()


# ---------------------------------------------------------------------------
# writer -> reader roundtrip


def test_pack_write_read_roundtrip(tmp_path):
    pack_dir = str(tmp_path / "pack")
    items = [("blob", f"content-{i}".encode() * (i + 1)) for i in range(50)]
    items.append(("tree", b""))
    with PackWriter(pack_dir) as w:
        oids = [w.add(t, c) for t, c in items]
    assert os.path.exists(w.pack_path) and os.path.exists(w.idx_path)

    pack = Packfile(w.pack_path)
    assert pack.count == len(items)
    for oid, (t, c) in zip(oids, items):
        got = pack.read(bytes.fromhex(oid))
        assert got == (t, c)
    assert pack.read(b"\x00" * 20) is None


def test_pack_writer_dedupes(tmp_path):
    with PackWriter(str(tmp_path)) as w:
        a = w.add("blob", b"same")
        b = w.add("blob", b"same")
    assert a == b
    assert Packfile(w.pack_path).count == 1


def test_pack_writer_abort_leaves_nothing(tmp_path):
    with pytest.raises(RuntimeError):
        with PackWriter(str(tmp_path)):
            raise RuntimeError("boom")
    assert [f for f in os.listdir(tmp_path) if not f.startswith(".")] == []


def test_odb_reads_through_packs(tmp_path):
    objects_dir = str(tmp_path / "objects")
    os.makedirs(objects_dir)
    odb = ObjectDb(objects_dir)
    oids = odb.write_pack([("blob", b"alpha"), ("blob", b"beta")])
    assert len(oids) == 2
    # nothing loose
    assert not any(len(d) == 2 for d in os.listdir(objects_dir))
    assert odb.read_blob(oids[0]) == b"alpha"
    assert odb.contains(oids[1])
    assert sorted(odb.iter_oids()) == sorted(oids)
    assert list(odb.find_oids_with_prefix(oids[0][:3])) == [oids[0]]


def test_bulk_pack_redirects_writes(tmp_path):
    objects_dir = str(tmp_path / "objects")
    os.makedirs(objects_dir)
    odb = ObjectDb(objects_dir)
    with odb.bulk_pack():
        oid = odb.write_blob(b"bulk feature")
    assert odb.read_blob(oid) == b"bulk feature"
    pack_dir = os.path.join(objects_dir, "pack")
    assert any(f.endswith(".pack") for f in os.listdir(pack_dir))
    # loose store untouched
    assert not os.path.exists(os.path.join(objects_dir, oid[:2]))


def test_bulk_pack_abort_on_error(tmp_path):
    objects_dir = str(tmp_path / "objects")
    os.makedirs(objects_dir)
    odb = ObjectDb(objects_dir)
    with pytest.raises(RuntimeError):
        with odb.bulk_pack():
            odb.write_blob(b"doomed")
            raise RuntimeError("crash mid-import")
    pack_dir = os.path.join(objects_dir, "pack")
    assert not os.path.isdir(pack_dir) or not any(
        f.endswith(".pack") for f in os.listdir(pack_dir)
    )


# ---------------------------------------------------------------------------
# delta resolution (hand-assembled pack: git fixtures here contain no deltas,
# but real git repacks produce them heavily)


def _varint_header(type_code, size):
    byte0 = (type_code << 4) | (size & 0x0F)
    size >>= 4
    out = bytearray()
    while size:
        out.append(byte0 | 0x80)
        byte0 = size & 0x7F
        size >>= 7
    out.append(byte0)
    return bytes(out)


def _delta_size(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ofs_backref(offset):
    # git's modified big-endian varint
    out = [offset & 0x7F]
    offset >>= 7
    while offset:
        offset -= 1
        out.insert(0, 0x80 | (offset & 0x7F))
        offset >>= 7
    return bytes(out)


def _make_delta(base, result):
    """A delta that copies the first half of base then inserts the rest of
    result literally."""
    half = len(base) // 2
    assert result[:half] == base[:half]
    delta = bytearray()
    delta += _delta_size(len(base))
    delta += _delta_size(len(result))
    # copy op: offset 0, size half  (op 0x80 | size-bytes flags)
    delta.append(0x80 | 0x10)  # one size byte, no offset bytes
    delta.append(half)
    rest = result[half:]
    assert 0 < len(rest) < 127
    delta.append(len(rest))
    delta += rest
    return bytes(delta)


def test_delta_pack_resolution(tmp_path):
    base = b"A" * 40 + b"B" * 24
    derived_ofs = base[:32] + b"ofs-tail"
    derived_ref = base[:32] + b"ref-tail"

    base_sha = _obj_sha("blob", base)
    ofs_sha = _obj_sha("blob", derived_ofs)
    ref_sha = _obj_sha("blob", derived_ref)

    records = []
    body = bytearray()
    # base record
    base_off = 12
    rec = _varint_header(OBJ_BLOB, len(base)) + zlib.compress(base)
    records.append((base_sha, rec, base_off))
    body += rec
    # ofs-delta record
    ofs_off = base_off + len(rec)
    delta = _make_delta(base, derived_ofs)
    rec = (
        _varint_header(6, len(delta))
        + _ofs_backref(ofs_off - base_off)
        + zlib.compress(delta)
    )
    records.append((ofs_sha, rec, ofs_off))
    body += rec
    # ref-delta record
    ref_off = ofs_off + len(rec)
    delta = _make_delta(base, derived_ref)
    rec = _varint_header(7, len(delta)) + base_sha + zlib.compress(delta)
    records.append((ref_sha, rec, ref_off))
    body += rec

    pack_bytes = b"PACK" + struct.pack(">II", 2, 3) + bytes(body)
    pack_sha = hashlib.sha1(pack_bytes).digest()
    pack_bytes += pack_sha

    pack_path = str(tmp_path / "pack-test.pack")
    with open(pack_path, "wb") as f:
        f.write(pack_bytes)
    from binascii import crc32

    write_pack_index(
        str(tmp_path / "pack-test.idx"),
        [(sha, crc32(rec) & 0xFFFFFFFF, off) for sha, rec, off in records],
        pack_sha,
    )

    pack = Packfile(pack_path)
    assert pack.read(base_sha) == ("blob", base)
    assert pack.read(ofs_sha) == ("blob", derived_ofs)
    assert pack.read(ref_sha) == ("blob", derived_ref)


def test_apply_delta_copy_sizes():
    base = bytes(range(256)) * 200  # 51200 bytes
    # copy whole base with size 0 encoding (0x10000 would exceed; use explicit)
    delta = bytearray()
    delta += _delta_size(len(base))
    delta += _delta_size(len(base))
    delta.append(0x80 | 0x30)  # two size bytes
    delta += struct.pack("<H", len(base))
    assert apply_delta(base, bytes(delta)) == base


# ---------------------------------------------------------------------------
# packed-refs


def test_packed_refs(tmp_path):
    gitdir = str(tmp_path)
    os.makedirs(os.path.join(gitdir, "refs", "heads"))
    with open(os.path.join(gitdir, "packed-refs"), "w") as f:
        f.write("# pack-refs with: peeled fully-peeled sorted \n")
        f.write("aa" * 20 + " refs/heads/main\n")
        f.write("bb" * 20 + " refs/tags/v1\n")
        f.write("^" + "cc" * 20 + "\n")  # peel line: skipped
    refs = RefStore(gitdir)
    assert refs.get("refs/heads/main") == "aa" * 20
    assert refs.get("refs/tags/v1") == "bb" * 20
    assert refs.exists("refs/tags/v1")
    assert dict(refs.iter_refs()) == {
        "refs/heads/main": "aa" * 20,
        "refs/tags/v1": "bb" * 20,
    }
    # loose shadows packed
    refs.set("refs/heads/main", "dd" * 20)
    assert refs.get("refs/heads/main") == "dd" * 20
    # delete removes from packed-refs too — preserving the header and the
    # peel line of the ref that remains
    refs.delete("refs/heads/main")
    assert refs.get("refs/heads/main") is None
    with open(os.path.join(gitdir, "packed-refs")) as f:
        content = f.read()
    assert content.startswith("# pack-refs")
    assert "^" + "cc" * 20 in content  # v1's peel line survives
    # deleting the tag removes its peel line with it
    refs.delete("refs/tags/v1")
    assert refs.get("refs/tags/v1") is None
    with open(os.path.join(gitdir, "packed-refs")) as f:
        assert "^" not in f.read()


# ---------------------------------------------------------------------------
# reference fixtures as oracles

from conftest import REF_DATA as REF_FIXTURES
from conftest import needs_ref_fixtures as needs_fixtures


@pytest.fixture
def points_fixture(tmp_path):
    with tarfile.open(os.path.join(REF_FIXTURES, "points.tgz")) as tf:
        tf.extractall(str(tmp_path), filter="data")
    return str(tmp_path / "points")


@needs_fixtures
def test_reference_fixture_log(points_fixture, cli_runner, monkeypatch):
    from kart_tpu.cli import cli

    monkeypatch.chdir(points_fixture)
    r = cli_runner.invoke(cli, ["log", "--oneline"])
    assert r.exit_code == 0, r.output
    lines = r.output.strip().splitlines()
    # known-answer constants from the reference's tests/conftest.py
    assert lines[0].startswith("1582725 ")
    assert "Improve naming on Coromandel East coast" in lines[0]
    assert "Import from nz-pa-points-topo-150k.gpkg" in lines[1]


@needs_fixtures
def test_reference_fixture_diff_feature_count(
    points_fixture, cli_runner, monkeypatch
):
    from kart_tpu.cli import cli

    monkeypatch.chdir(points_fixture)
    r = cli_runner.invoke(cli, ["data", "ls"])
    assert r.exit_code == 0, r.output
    assert r.output.strip() == "nz_pa_points_topo_150k"

    r = cli_runner.invoke(
        cli, ["diff", "HEAD^...HEAD", "-o", "feature-count"]
    )
    assert r.exit_code == 0, r.output
    assert "5 features changed" in r.output


@needs_fixtures
def test_reference_fixture_feature_values(points_fixture, monkeypatch):
    """Read a feature through the full V3 decode stack and check the row
    count the reference's conftest promises (POINTS.ROWCOUNT = 2143)."""
    monkeypatch.chdir(points_fixture)
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(".")
    structure = repo.structure("HEAD")
    (ds,) = list(structure.datasets)
    assert ds.path == "nz_pa_points_topo_150k"
    assert ds.feature_count == 2143
    feature = ds.get_feature(1)
    assert feature["fid"] == 1
    assert feature["t50_fid"] == 2426271


@needs_fixtures
@pytest.mark.parametrize(
    "archive,layer,rowcount,head_sha",
    [
        # known-answer constants from /root/reference/tests/conftest.py
        ("polygons", "nz_waca_adjustments", 228,
         "3f7166eebd11876a9b473a67ed2f66a200493b69"),
        ("table", "countiestbl", 3141,
         "f404fcd4ac2a411ef7bb32070e9ffa663374d875"),
    ],
)
def test_reference_fixture_matrix(
    tmp_path, monkeypatch, archive, layer, rowcount, head_sha
):
    """Every fixture family the reference's conftest promises constants for
    opens, lists, counts, and reads through our pack + V3 decode stack."""
    with tarfile.open(os.path.join(REF_FIXTURES, f"{archive}.tgz")) as tf:
        tf.extractall(str(tmp_path), filter="data")
    monkeypatch.chdir(str(tmp_path / archive))

    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(".")
    assert repo.head_commit_oid == head_sha
    structure = repo.structure("HEAD")
    (ds,) = list(structure.datasets)
    assert ds.path == layer
    assert ds.feature_count == rowcount


@needs_fixtures
def test_reference_fixture_string_pks(tmp_path, monkeypatch):
    """string-pks uses the msgpack-hash path encoder: every feature path
    must decode and every feature read back through our stack."""
    with tarfile.open(os.path.join(REF_FIXTURES, "string-pks.tgz")) as tf:
        tf.extractall(str(tmp_path), filter="data")
    monkeypatch.chdir(str(tmp_path / "string-pks"))

    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(".")
    structure = repo.structure("HEAD")
    (ds,) = list(structure.datasets)
    features = list(ds.features())
    assert len(features) == ds.feature_count > 0
    pk_col = ds.schema.pk_columns[0]
    assert all(isinstance(f[pk_col.name], str) for f in features[:10])


@needs_fixtures
def test_reference_fixture_all_types(tmp_path, monkeypatch):
    """The types fixture exercises every V2/V3 data type through our decode
    stack; known-answer values from the reference's own test data."""
    from conftest import extract_ref_archive

    src = extract_ref_archive(tmp_path, "types.tgz")
    monkeypatch.chdir(src)
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(".")
    (ds,) = list(repo.datasets("HEAD"))
    assert ds.path == "manytypes"
    f = next(iter(ds.features()))
    assert f["int8"] == 0x12
    assert f["int16"] == 0x1234
    assert f["int32"] == 0x12345678
    assert f["int64"] == 0x1234567890ABCDEF
    assert f["float32"] == 32.03125
    assert f["float64"] == 64.015625
    assert f["text"] == "foo" and f["text100"] == "bar"
    assert f["blob"].startswith(b"\x89PNG")
    assert f["boolean"] is True
    assert f["numeric10_5"] == "123.456"
    assert f["date"] == "2000-01-01"
    assert f["time"] == "18:19:20"
    assert f["timestamp"] == "2000-01-01T11:12:13"
    assert f["timestampUTC"] == "2001-01-01T18:19:20"
    assert f["interval"] == "P3D"


@needs_fixtures
def test_reference_fixture_custom_crs(tmp_path, monkeypatch):
    """Custom (non-EPSG) CRS identifiers round-trip through meta items."""
    from conftest import extract_ref_archive

    src = extract_ref_archive(tmp_path, "custom_crs.tgz")
    monkeypatch.chdir(src)
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(".")
    (ds,) = list(repo.datasets("HEAD"))
    ids = ds.crs_identifiers()
    assert ids == ["koordinates.com:100002"]
    wkt = ds.get_crs_definition(ids[0])
    assert "koordinates.com" in wkt or "NZGD2000" in wkt or len(wkt) > 100


@needs_fixtures
def test_reference_fixture_pk_second_column(tmp_path, monkeypatch):
    """Primary key not in column position 0 (pk-second fixture): decode,
    path encoding, and read-back all honour pk_index."""
    from conftest import extract_ref_archive

    src = extract_ref_archive(tmp_path, "pk-second.tgz")
    monkeypatch.chdir(src)
    from kart_tpu.core.repo import KartRepo

    repo = KartRepo(".")
    (ds,) = list(repo.datasets("HEAD"))
    pk = ds.schema.pk_columns[0]
    cols = [c.name for c in ds.schema.columns]
    assert cols.index(pk.name) == 1
    first = next(iter(ds.features()))
    again = ds.get_feature([first[pk.name]])
    assert again == first


@needs_fixtures
def test_import_3d_points_gpkg(tmp_path, monkeypatch, cli_runner):
    """Z-coordinate geometries import with POINT Z schema and round-trip
    has_z through the V3 codec (gpkg-3d-points fixture)."""
    import os

    from conftest import REF_DATA, extract_ref_archive

    gpkg_dir = extract_ref_archive(tmp_path / "x", "gpkg-3d-points.tgz")
    gpkg = os.path.join(gpkg_dir, "points-3d.gpkg")

    from kart_tpu.cli import cli
    from kart_tpu.core.repo import KartRepo

    r = cli_runner.invoke(cli, ["init", str(tmp_path / "r")])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(tmp_path / "r")
    KartRepo(".").config.set_many(
        {"user.name": "T", "user.email": "t@example.com"}
    )
    r = cli_runner.invoke(cli, ["import", gpkg, "--no-checkout"])
    assert r.exit_code == 0, r.output
    (ds,) = list(KartRepo(".").datasets("HEAD"))
    geom_col = ds.schema.first_geometry_column
    assert geom_col.extra_type_info.get("geometryType") == "POINT Z"
    f = next(iter(ds.features()))
    g = f[ds.geom_column_name]
    assert g.has_z
    assert g.to_wkt().startswith("POINT Z ")


@needs_fixtures
@pytest.mark.parametrize(
    "archive,datasets",
    [
        ("au-census", 2),
        ("editing", 1),
        ("empty-geometry", 2),
        ("meta-updates", 1),
    ],
)
def test_reference_fixture_fsck_clean(tmp_path, monkeypatch, cli_runner, archive, datasets):
    """Every remaining reference repo fixture opens and passes a full fsck
    (object hashes, refs, dataset decode)."""
    from conftest import extract_ref_archive

    src = extract_ref_archive(tmp_path, f"{archive}.tgz")
    monkeypatch.chdir(src)
    from kart_tpu.cli import cli
    from kart_tpu.core.repo import KartRepo

    assert len(list(KartRepo(".").datasets("HEAD"))) == datasets
    r = cli_runner.invoke(cli, ["fsck"])
    assert r.exit_code == 0, r.output
    assert "No errors found" in r.output


def test_read_batch_matches_per_object(tmp_path):
    """The native batch inflate returns byte-identical content to the
    per-object path, skips delta records (type 0) for the fallback, and
    omits shas the pack doesn't hold."""
    import numpy as np

    objects_dir = str(tmp_path / "objects")
    os.makedirs(objects_dir)
    odb = ObjectDb(objects_dir)
    contents = [b"blob-%d" % i * (i % 7 + 1) for i in range(500)]
    oids = odb.write_pack([("blob", c) for c in contents])
    (pack,) = odb.packs.packs
    shas = [bytes.fromhex(o) for o in oids]
    from kart_tpu import native

    if native.load_io() is None:
        pytest.skip("native IO lib unavailable")
    got = pack.read_batch(shas + [b"\xff" * 20])
    assert len(got) == len(shas)
    for sha, content in zip(shas, contents):
        assert got[sha] == ("blob", content)

    # odb-level: blob filter + hex mapping
    batch = odb.read_blobs_batch(oids[:10] + ["ff" * 20])
    assert batch == {o: c for o, c in zip(oids[:10], contents[:10])}


@needs_fixtures
def test_read_batch_on_reference_pack(tmp_path):
    """Batch reads over the reference's own packfiles (which contain real
    delta records) agree with the per-object reader for every object the
    batch resolves, and leave delta records to the fallback."""
    from conftest import extract_ref_archive

    repo_dir = extract_ref_archive(tmp_path, "points.tgz")
    pack_dir = None
    for root, dirs, files in os.walk(repo_dir):
        if any(f.endswith(".pack") for f in files):
            pack_dir = root
            break
    assert pack_dir is not None
    from kart_tpu.core.packs import PackCollection

    coll = PackCollection([pack_dir])
    shas = []
    for pack in coll.packs:
        shas.extend(pack.index.iter_shas())
    shas = shas[:5000]
    from kart_tpu import native

    if native.load_io() is None:
        pytest.skip("native IO lib unavailable")
    got = coll.read_batch(shas)
    assert got  # at least the non-delta records resolve
    for sha, (t, content) in list(got.items())[:2000]:
        assert coll.read(sha) == (t, content)
    # every sha still resolves through the fallback
    for sha in shas[:200]:
        assert coll.read(sha) is not None


def test_maybe_refresh_rate_limited(tmp_path):
    """Inside the racy-mtime window every lookup miss used to trigger a
    full rescan (ADVICE r3); now at most one rescan per interval."""
    from kart_tpu.core.packs import PackCollection

    d = tmp_path / "pack"
    d.mkdir()
    pc = PackCollection([str(d)])
    assert pc.packs == []  # initial scan (fresh dir: inside racy window)
    assert pc.maybe_refresh() is True  # racy window: one rescan allowed
    assert pc.packs == []
    # immediately after, further misses are rate-limited: no rescan storm
    assert pc.maybe_refresh() is False
    assert pc.maybe_refresh() is False
    # after the rate window passes, the racy rescan is allowed again
    pc._last_refresh_ns -= 10**9
    assert pc.maybe_refresh() is True


def test_prepare_pack_index_prefix_ties_and_dups():
    """The idx sort takes one u64 argsort on the 8-byte sha prefix plus a
    tie fixup; force shared prefixes (never produced by real SHA-1 at test
    scale, so synthesised) and full duplicates, and pin the table order
    against a plain python lexicographic sort."""
    import numpy as np

    from kart_tpu.core.packs import prepare_pack_index

    rng = np.random.default_rng(7)
    n = 5000
    shas = rng.integers(0, 256, (n, 20), dtype=np.uint8)
    shas[100:300, :8] = shas[100, :8]  # 200 rows share one prefix
    shas[400:500, :8] = shas[400, :8]  # 100 share another
    shas[600:605] = shas[600]  # 5 fully identical keys
    crcs = rng.integers(0, 2**32, n, dtype=np.uint32)
    offs = (np.arange(n, dtype=np.int64) * 97)

    tables = prepare_pack_index([], [(shas, crcs, offs)])

    fanout = np.frombuffer(tables[:1024], dtype=">u4")
    out_shas = np.frombuffer(
        tables[1024 : 1024 + 20 * n], dtype=np.uint8
    ).reshape(n, 20)
    out_crcs = np.frombuffer(tables[1024 + 20 * n : 1024 + 24 * n], dtype=">u4")
    out_offs = np.frombuffer(tables[1024 + 24 * n : 1024 + 28 * n], dtype=">u4")

    keys = [bytes(s) for s in shas]
    ref_rows = sorted(range(n), key=lambda i: keys[i])
    np.testing.assert_array_equal(
        out_shas, np.array([shas[i] for i in ref_rows])
    )
    # crc/offset tables follow the same permutation (dup keys: any of the
    # duplicates' payloads is acceptable at each slot)
    want = {}
    for i in range(n):
        want.setdefault(keys[i], set()).add((int(crcs[i]), int(offs[i])))
    for j in range(n):
        assert (int(out_crcs[j]), int(out_offs[j])) in want[bytes(out_shas[j])]
    counts = np.bincount(shas[:, 0], minlength=256)
    np.testing.assert_array_equal(fanout, np.cumsum(counts).astype(">u4"))


def test_pack_writer_batch_dedupe_across_batches(tmp_path):
    """The vectorised prefix probe must still catch exact duplicates that
    arrive in a LATER add_batch_raw call (cross-batch dedupe): the second
    write of the same content adds no entries and readers resolve every
    oid."""
    from kart_tpu import native
    from kart_tpu.core.packs import PackCollection, PackWriter

    if native.load_io() is None:
        pytest.skip("native IO lib unavailable")
    pack_dir = str(tmp_path / "pack")
    blobs_a = [b"payload-%d" % i for i in range(500)]
    blobs_b = [b"payload-%d" % i for i in range(250, 750)]  # 250 dupes
    with PackWriter(pack_dir) as w:
        first = w.add_batch_raw("blob", blobs_a)
        assert first is not None
        second = w.add_batch_raw("blob", blobs_b)
        assert second is not None
        assert w.object_count == 750  # not 1000
    packs = PackCollection([pack_dir])
    for blob, oid_row in zip(blobs_b, second):
        got = packs.read(bytes(oid_row))
        assert got == ("blob", blob)


def test_pack_writer_dedupe_run_stack_many_batches(tmp_path):
    """The prefix accumulator is a geometrically-merged run stack, not one
    re-merged array: after many clean batches the runs stay strictly
    size-decreasing (O(log n) of them), duplicates of the OLDEST batch are
    still caught, and the scalar add() path probes the runs too."""
    from kart_tpu import native
    from kart_tpu.core.packs import PackCollection, PackWriter

    if native.load_io() is None:
        pytest.skip("native IO lib unavailable")
    pack_dir = str(tmp_path / "pack")
    batches = [
        [b"batch%d-row%d" % (b, i) for i in range(64)] for b in range(9)
    ]
    with PackWriter(pack_dir) as w:
        oids = [w.add_batch_raw("blob", blobs) for blobs in batches]
        assert all(o is not None for o in oids)
        sizes = [c.size for c in w._seen_pref_chunks]
        assert sum(sizes) == 9 * 64
        assert sizes == sorted(sizes, reverse=True)
        assert len(sizes) <= 3  # binary counter: 9*64 rows -> runs 8,1 (*64)
        # duplicate the oldest batch (lives deep in the merged run) plus
        # fresh rows: dedupe must route through the slow path and keep one
        # copy of everything
        mixed = batches[0][:32] + [b"fresh-%d" % i for i in range(32)]
        third = w.add_batch_raw("blob", mixed)
        # scalar path probes the run stack as well
        assert w.add("blob", batches[0][0]) == bytes(oids[0][0]).hex()
        assert w.object_count == 9 * 64 + 32
    packs = PackCollection([pack_dir])
    for blob, oid_row in zip(mixed, third):
        assert packs.read(bytes(oid_row)) == ("blob", blob)


def test_first_pack_scan_publishes_atomically_to_concurrent_readers(tmp_path, monkeypatch):
    """Regression (ISSUE 10 storm): the first lazy pack scan used to assign
    an empty list and append packs one by one — a concurrent reader (the
    threading server's other handlers; 16 cold tile requests on a fresh
    server) could observe the partial list and report reachable objects as
    missing. The scan must publish a complete list atomically."""
    import threading
    import time as _time

    from kart_tpu.core import packs as packs_mod

    pack_dir = str(tmp_path / "pack")
    with PackWriter(pack_dir) as w:
        oid = w.add("blob", b"present")
    pc = PackCollection([pack_dir])

    # make the scanner's Packfile construction slow enough that the reader
    # thread provably runs while the scan is mid-flight
    real_init = packs_mod.Packfile.__init__
    scanning = threading.Event()

    def slow_init(self, *args, **kwargs):
        scanning.set()
        _time.sleep(0.3)
        real_init(self, *args, **kwargs)

    monkeypatch.setattr(packs_mod.Packfile, "__init__", slow_init)
    scanner = threading.Thread(target=lambda: pc.packs)
    scanner.start()
    assert scanning.wait(5)
    # mid-scan read: must run (or wait on) a complete scan, never see a
    # partially-populated list
    got = pc.read(bytes.fromhex(oid))
    scanner.join()
    assert got == ("blob", b"present")
