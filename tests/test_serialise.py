import base64
import hashlib

from kart_tpu.core import serialise
from kart_tpu.geometry import Geometry


def test_msgpack_roundtrip_basic():
    value = {"a": 1, "b": [1, 2.5, None, True, "x", b"raw"]}
    assert serialise.msg_unpack(serialise.msg_pack(value)) == value


def test_msgpack_tuple_becomes_list():
    assert serialise.msg_unpack(serialise.msg_pack((1, 2))) == [1, 2]


def test_msgpack_geometry_extension():
    g = Geometry.from_wkt("POINT (1 2)")
    packed = serialise.msg_pack([g])
    # extension code G = 0x47
    assert b"\x47" in packed or packed.find(bytes([0xC7])) >= 0
    out = serialise.msg_unpack(packed)
    assert isinstance(out[0], Geometry)
    assert bytes(out[0]) == bytes(g)


def test_hexhash_is_truncated_sha256():
    assert serialise.hexhash(b"abc") == hashlib.sha256(b"abc").hexdigest()[:40]
    # str and bytes hash identically
    assert serialise.hexhash("abc") == serialise.hexhash(b"abc")


def test_b64hash_width():
    h = serialise.b64hash(b"abc")
    assert len(base64.urlsafe_b64decode(h)) == 20


def test_uint32hash():
    v = serialise.uint32hash(b"abc")
    assert 0 <= v < 2**32
