"""CLI merge/conflicts/resolve flow (reference: tests/test_merge.py CLI
cases)."""

import json
import os
import sqlite3

import pytest
from click.testing import CliRunner

from helpers import create_points_gpkg, wc_connect
from kart_tpu.cli import cli


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def repo_dir(tmp_path, runner, monkeypatch):
    gpkg = create_points_gpkg(str(tmp_path / "source.gpkg"), n=10)
    repo_dir = tmp_path / "repo"
    r = runner.invoke(cli, ["init", str(repo_dir), "--workingcopy-location", "wc.gpkg"])
    assert r.exit_code == 0, r.output
    monkeypatch.chdir(repo_dir)
    from kart_tpu.core.repo import KartRepo

    KartRepo(str(repo_dir)).config.set_many(
        {"user.name": "Tester", "user.email": "t@example.com"}
    )
    r = runner.invoke(cli, ["import", str(gpkg)])
    assert r.exit_code == 0, r.output
    return repo_dir


def wc_edit(repo_dir, sql):
    con = wc_connect(repo_dir / "wc.gpkg")
    con.executescript(sql)
    con.commit()
    con.close()


def commit_edit(runner, repo_dir, sql, message):
    wc_edit(repo_dir, sql)
    r = runner.invoke(cli, ["commit", "-m", message])
    assert r.exit_code == 0, r.output


def make_conflict(runner, repo_dir):
    """main and alt both edit fid=3's name differently."""
    r = runner.invoke(cli, ["branch", "alt"])
    assert r.exit_code == 0, r.output
    commit_edit(
        runner, repo_dir, "UPDATE points SET name='ours-3' WHERE fid=3", "ours edit"
    )
    r = runner.invoke(cli, ["switch", "alt"])
    assert r.exit_code == 0, r.output
    commit_edit(
        runner, repo_dir, "UPDATE points SET name='theirs-3' WHERE fid=3", "theirs edit"
    )
    r = runner.invoke(cli, ["switch", "main"])
    assert r.exit_code == 0, r.output


def test_merge_fast_forward(repo_dir, runner):
    r = runner.invoke(cli, ["branch", "alt"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["switch", "alt"])
    commit_edit(
        runner, repo_dir, "UPDATE points SET name='x' WHERE fid=1", "edit on alt"
    )
    r = runner.invoke(cli, ["switch", "main"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["merge", "alt"])
    assert r.exit_code == 0, r.output
    assert "Fast-forward" in r.output


def test_merge_clean(repo_dir, runner):
    r = runner.invoke(cli, ["branch", "alt"])
    commit_edit(
        runner, repo_dir, "UPDATE points SET name='ours-1' WHERE fid=1", "ours"
    )
    r = runner.invoke(cli, ["switch", "alt"])
    commit_edit(
        runner, repo_dir, "UPDATE points SET name='theirs-2' WHERE fid=2", "theirs"
    )
    r = runner.invoke(cli, ["switch", "main"])
    r = runner.invoke(cli, ["merge", "alt", "-o", "json"])
    assert r.exit_code == 0, r.output
    body = json.loads(r.output)["kart.merge/v1"]
    assert "commit" in body
    # both edits present in the working copy
    con = wc_connect(repo_dir / "wc.gpkg")
    names = dict(con.execute("SELECT fid, name FROM points WHERE fid IN (1,2)"))
    con.close()
    assert names == {1: "ours-1", 2: "theirs-2"}


def test_merge_conflict_resolve_continue(repo_dir, runner):
    make_conflict(runner, repo_dir)
    r = runner.invoke(cli, ["merge", "alt"])
    # entering merging state is success (reference exit-code semantics)
    assert r.exit_code == 0
    assert "conflict" in r.output.lower()

    r = runner.invoke(cli, ["status"])
    assert r.exit_code == 0

    r = runner.invoke(cli, ["conflicts"])
    assert r.exit_code == 0
    assert "points:feature:3" in r.output

    r = runner.invoke(cli, ["conflicts", "-o", "json"])
    body = json.loads(r.output)["kart.conflicts/v1"]
    # reference shape: {dataset: {"feature": {pk: {version: value}}}}
    versions = body["points"]["feature"]["3"]
    assert versions["ours"]["name"] == "ours-3"
    assert versions["theirs"]["name"] == "theirs-3"

    r = runner.invoke(cli, ["resolve", "points:feature:3", "--with", "theirs"])
    assert r.exit_code == 0, r.output
    assert "All conflicts resolved" in r.output

    r = runner.invoke(cli, ["conflicts"])
    assert r.exit_code == 0
    assert r.output.strip() == ""  # reference: empty hierarchy, no output
    r = runner.invoke(cli, ["conflicts", "--exit-code"])
    assert r.exit_code == 0

    r = runner.invoke(cli, ["merge", "--continue"])
    assert r.exit_code == 0, r.output

    con = wc_connect(repo_dir / "wc.gpkg")
    (name,) = con.execute("SELECT name FROM points WHERE fid=3").fetchone()
    con.close()
    assert name == "theirs-3"


def test_merge_abort(repo_dir, runner):
    make_conflict(runner, repo_dir)
    r = runner.invoke(cli, ["merge", "alt"])
    assert r.exit_code == 0
    r = runner.invoke(cli, ["merge", "--abort"])
    assert r.exit_code == 0, r.output
    con = wc_connect(repo_dir / "wc.gpkg")
    (name,) = con.execute("SELECT name FROM points WHERE fid=3").fetchone()
    con.close()
    assert name == "ours-3"
    # merge again works
    r = runner.invoke(cli, ["merge", "alt", "--dry-run"])
    assert r.exit_code == 0, r.output
    assert "1 conflicts (dry run)" in r.output


def test_resolve_with_file(repo_dir, runner, tmp_path):
    make_conflict(runner, repo_dir)
    runner.invoke(cli, ["merge", "alt"])
    geojson = {
        "type": "Feature",
        "id": 3,
        "geometry": {"type": "Point", "coordinates": [103.0, -40.3]},
        "properties": {"fid": 3, "name": "resolved-3", "rating": 1.5},
    }
    path = tmp_path / "res.geojson"
    path.write_text(json.dumps(geojson))
    r = runner.invoke(
        cli, ["resolve", "points:feature:3", "--with-file", str(path)]
    )
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["merge", "--continue"])
    assert r.exit_code == 0, r.output
    con = wc_connect(repo_dir / "wc.gpkg")
    (name,) = con.execute("SELECT name FROM points WHERE fid=3").fetchone()
    con.close()
    assert name == "resolved-3"


def test_merge_no_conflicts_command_outside_merge(repo_dir, runner):
    r = runner.invoke(cli, ["conflicts"])
    assert r.exit_code != 0
    r = runner.invoke(cli, ["merge", "--continue"])
    assert r.exit_code != 0


def test_meta_conflict_renders_text_values(repo_dir, runner):
    """Meta items (title etc.) are plain text, not msgpack — the conflicts
    output must show the actual strings."""
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.diff.structs import (
        DatasetDiff,
        Delta,
        DeltaDiff,
        KeyValue,
        RepoDiff,
    )

    repo = KartRepo(str(repo_dir))

    def meta_commit(title, ref):
        structure = repo.structure(ref)
        meta_diff = DeltaDiff()
        meta_diff.add_delta(
            Delta.update(
                KeyValue(("title", "points title")), KeyValue(("title", title))
            )
        )
        ds_diff = DatasetDiff()
        ds_diff["meta"] = meta_diff
        repo_diff = RepoDiff()
        repo_diff["points"] = ds_diff
        return structure.commit_diff(repo_diff, f"retitle {title}")

    r = runner.invoke(cli, ["branch", "alt"])
    assert r.exit_code == 0, r.output
    meta_commit("ours title", "HEAD")
    meta_commit("theirs title", "refs/heads/alt")
    r = runner.invoke(cli, ["merge", "alt"])
    assert r.exit_code == 0
    r = runner.invoke(cli, ["conflicts", "-o", "json"])
    body = json.loads(r.output)["kart.conflicts/v1"]
    versions = body["points"]["meta"]["title"]
    assert versions["ours"] == "ours title"
    assert versions["theirs"] == "theirs title"
    assert versions["ancestor"] == "points title"


@pytest.mark.parametrize(
    "archive,layer,expected_pks",
    [
        ("points", "nz_pa_points_topo_150k", None),
        ("polygons", "nz_waca_adjustments",
         [98001, 1452332, 1456853, 1456912]),
        ("table", "countiestbl", None),
    ],
)
def test_reference_conflicts_scenarios(
    tmp_path, monkeypatch, archive, layer, expected_pks
):
    """The reference's premade 3-way merge scenarios (ancestor/ours/theirs
    branches): our merge engine finds exactly the conflicts the reference's
    own tests expect (4 per scenario; polygons' pk set is asserted
    verbatim), and resolving with --with=ours completes the merge."""
    from conftest import REF_DATA, extract_ref_archive

    if not os.path.isdir(os.path.join(REF_DATA, "conflicts")):
        pytest.skip("reference fixtures not available")
    src = extract_ref_archive(tmp_path, f"conflicts/{archive}.tgz")
    monkeypatch.chdir(src)
    runner = CliRunner()
    r = runner.invoke(cli, ["merge", "theirs_branch"])
    assert r.exit_code == 0, r.output
    assert "4 conflicts" in r.output

    r = runner.invoke(cli, ["conflicts", "-o", "json"])
    assert r.exit_code == 0, r.output
    body = json.loads(r.output)["kart.conflicts/v1"]
    feats = body[layer]["feature"]
    assert len(feats) == 4
    if expected_pks is not None:
        assert sorted(int(pk) for pk in feats) == sorted(expected_pks)
    # summaries match the reference's own expected output shapes
    r = runner.invoke(cli, ["conflicts", "-s", "-o", "json"])
    sbody = json.loads(r.output)["kart.conflicts/v1"]
    assert sbody == {layer: {"feature": sorted(feats, key=lambda k: int(k))}}
    r = runner.invoke(cli, ["conflicts", "-ss", "-o", "json"])
    assert json.loads(r.output)["kart.conflicts/v1"] == {layer: {"feature": 4}}
    # ... and the -s / -ss TEXT renderings are byte-exact vs the reference's
    # expected output (tests/test_conflicts.py:test_summarise_conflicts)
    r = runner.invoke(cli, ["conflicts", "-s"])
    pks_sorted = sorted(feats, key=lambda k: int(k))
    assert r.output.splitlines() == [
        f"{layer}:",
        f"    {layer}:feature:",
        *[f"        {layer}:feature:{pk}" for pk in pks_sorted],
        "",
    ], r.output
    r = runner.invoke(cli, ["conflicts", "-ss"])
    assert r.output.splitlines() == [
        f"{layer}:",
        f"    {layer}:feature: 4 conflicts",
        "",
    ], r.output

    labels = [f"{layer}:feature:{pk}" for pk in feats]
    for label in labels:
        r = runner.invoke(cli, ["resolve", label, "--with=ours"])
        assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["merge", "--continue", "-m", "merged"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["log", "--oneline"])
    assert "merged" in r.output.splitlines()[0]


def test_conflicts_output_options(repo_dir, runner):
    """geojson / --flat / --exit-code / filters / --crs on the conflicts
    command (reference option surface, kart/conflicts.py:219-262)."""
    make_conflict(runner, repo_dir)
    r = runner.invoke(cli, ["merge", "alt"])
    assert r.exit_code == 0

    r = runner.invoke(cli, ["conflicts", "-o", "geojson"])
    fc = json.loads(r.output)
    assert fc["type"] == "FeatureCollection"
    ids = sorted(f["id"] for f in fc["features"])
    assert ids == [
        "points:feature:3:ancestor",
        "points:feature:3:ours",
        "points:feature:3:theirs",
    ]
    by_id = {f["id"]: f for f in fc["features"]}
    assert by_id["points:feature:3:ours"]["properties"]["name"] == "ours-3"
    assert by_id["points:feature:3:ours"]["geometry"]["type"] == "Point"

    r = runner.invoke(cli, ["conflicts", "--flat", "-o", "json"])
    body = json.loads(r.output)["kart.conflicts/v1"]
    assert body["points:feature:3:ours"]["name"] == "ours-3"

    r = runner.invoke(cli, ["conflicts", "--exit-code"])
    assert r.exit_code == 1

    # filters: non-matching filter yields an empty hierarchy
    r = runner.invoke(cli, ["conflicts", "points:feature:999", "-o", "json"])
    assert json.loads(r.output)["kart.conflicts/v1"] == {}
    r = runner.invoke(cli, ["conflicts", "points", "-o", "json"])
    assert "3" in json.loads(r.output)["kart.conflicts/v1"]["points"]["feature"]

    # --crs reprojects the version geometries (EPSG:3857 metres, not degrees)
    r = runner.invoke(
        cli, ["conflicts", "--crs", "EPSG:3857", "-o", "json"]
    )
    versions = json.loads(r.output)["kart.conflicts/v1"]["points"]["feature"]["3"]
    from kart_tpu.geometry import Geometry

    hexwkb = versions["ours"]["geom"]
    geom = Geometry.from_hex_wkb(hexwkb)
    x, _y = json.loads(json.dumps(geom.to_geojson()))["coordinates"][:2]
    assert abs(x) > 1_000_000  # web-mercator metres


def test_conflicts_text_full_listing_shape(repo_dir, runner):
    """Full text listing follows the reference hierarchy: dataset, part,
    pk, then coloured version blocks with 40-column field lines
    (reference: tests/test_conflicts.py:test_list_conflicts)."""
    make_conflict(runner, repo_dir)
    runner.invoke(cli, ["merge", "alt"])
    r = runner.invoke(cli, ["conflicts"])
    lines = r.output.splitlines()
    assert lines[0] == "points:"
    assert lines[1] == "    points:feature:"
    assert lines[2] == "        points:feature:3:"
    assert lines[3] == "            points:feature:3:ancestor:"
    assert any(line.endswith("name = ours-3") for line in lines)
    assert any(line.endswith("name = theirs-3") for line in lines)
    ours_ix = lines.index("            points:feature:3:ours:")
    theirs_ix = lines.index("            points:feature:3:theirs:")
    assert 3 < ours_ix < theirs_ix


def test_conflicts_exit_code_respects_filters(repo_dir, runner):
    """--exit-code / quiet answer 'are there conflicts MATCHING the
    filter', not 'any conflicts anywhere' (review finding)."""
    make_conflict(runner, repo_dir)
    runner.invoke(cli, ["merge", "alt"])
    r = runner.invoke(cli, ["conflicts", "points:feature:999", "--exit-code"])
    assert r.exit_code == 0
    r = runner.invoke(cli, ["conflicts", "points:feature:999", "-o", "quiet"])
    assert r.exit_code == 0
    r = runner.invoke(cli, ["conflicts", "points", "--exit-code"])
    assert r.exit_code == 1


def test_conflicts_invalid_crs_errors(repo_dir, runner):
    make_conflict(runner, repo_dir)
    runner.invoke(cli, ["merge", "alt"])
    r = runner.invoke(cli, ["conflicts", "--crs", "EPSG:999999", "-o", "json"])
    assert r.exit_code != 0


def test_conflicts_flat_summarise(repo_dir, runner):
    make_conflict(runner, repo_dir)
    runner.invoke(cli, ["merge", "alt"])
    r = runner.invoke(cli, ["conflicts", "--flat", "-s", "-o", "json"])
    assert json.loads(r.output)["kart.conflicts/v1"] == ["points:feature:3"]
    r = runner.invoke(cli, ["conflicts", "--flat", "-ss", "-o", "json"])
    assert json.loads(r.output)["kart.conflicts/v1"] == 1


def test_resolve_each_way_reference_scenario(tmp_path, monkeypatch):
    """Mirror of the reference's test_resolve_with_version: on its premade
    conflicting polygons repo, resolve the 4 conflicts with ancestor / ours
    / theirs / delete respectively and verify each outcome lands in the
    merged tree (reference: tests/test_resolve.py:36-110)."""
    from conftest import REF_DATA, extract_ref_archive

    if not os.path.isdir(os.path.join(REF_DATA, "conflicts")):
        pytest.skip("reference fixtures not available")
    src = extract_ref_archive(tmp_path, "conflicts/polygons.tgz")
    monkeypatch.chdir(src)
    runner = CliRunner()
    r = runner.invoke(cli, ["merge", "theirs_branch"])
    assert r.exit_code == 0, r.output

    # can't complete while conflicts remain
    r = runner.invoke(cli, ["merge", "--continue"])
    assert r.exit_code != 0

    from kart_tpu.core.repo import KartRepo
    from kart_tpu.merge.index import MergeIndex

    repo = KartRepo(str(src))
    mi = MergeIndex.read_from_repo(repo)
    labels = sorted(mi.conflicts, key=lambda l: int(l.rsplit(":", 1)[1]))
    assert len(labels) == 4
    versions_by_label = {
        label: {
            name: getattr(mi.conflicts[label], name)
            for name in ("ancestor", "ours", "theirs")
        }
        for label in labels
    }
    # 98001 is add/add (no ancestor): the reference resolves it to
    # ancestor anyway — "that version doesn't exist" acts as delete
    # (reference: test_resolve.py "resolved to ancestor, but the ancestor
    # is None")
    assert versions_by_label[labels[0]]["ancestor"] is None
    resolutions = ["ancestor", "ours", "theirs", "delete"]
    for i, (label, how) in enumerate(zip(labels, resolutions)):
        r = runner.invoke(cli, ["resolve", label, f"--with={how}"])
        assert r.exit_code == 0, (label, how, r.output)
        remaining = MergeIndex.read_from_repo(repo)
        assert len(remaining.resolves) == i + 1

    r = runner.invoke(cli, ["merge", "--continue", "-m", "merged each way"])
    assert r.exit_code == 0, r.output

    ds = repo.structure("HEAD").datasets["nz_waca_adjustments"]
    pks = [int(l.rsplit(":", 1)[1]) for l in labels]
    # delete resolution: the feature is gone
    import pytest as _pytest

    from kart_tpu.core.odb import ObjectMissing

    # ancestor-of-add/add and delete resolutions: the features are gone
    for gone in (pks[0], pks[3]):
        with _pytest.raises((KeyError, ObjectMissing, LookupError)):
            ds.get_feature([gone])
    # ours/theirs resolutions exist
    for pk in (pks[1], pks[2]):
        assert ds.get_feature([pk])["id"] == pk


def test_resolve_with_file_multiple_features(tmp_path, monkeypatch):
    """Mirror of the reference's test_resolve_with_file: an add/add
    conflict resolved with a FeatureCollection carrying BOTH features
    (theirs re-keyed to a fresh pk) — both land in the merged tree
    (reference: tests/test_resolve.py:110-170)."""
    from conftest import REF_DATA, extract_ref_archive

    if not os.path.isdir(os.path.join(REF_DATA, "conflicts")):
        pytest.skip("reference fixtures not available")
    src = extract_ref_archive(tmp_path, "conflicts/polygons.tgz")
    monkeypatch.chdir(src)
    runner = CliRunner()

    r = runner.invoke(cli, ["diff", "ancestor_branch..ours_branch", "-o", "geojson"])
    assert r.exit_code == 0, r.output
    ours_geojson = json.loads(r.output)["features"][0]
    assert ours_geojson["id"] == "I::98001"
    r = runner.invoke(cli, ["diff", "ancestor_branch..theirs_branch", "-o", "geojson"])
    theirs_geojson = json.loads(r.output)["features"][0]
    assert theirs_geojson["id"] == "I::98001"

    r = runner.invoke(cli, ["merge", "theirs_branch"])
    assert r.exit_code == 0, r.output

    ours_geojson["id"] = "ours-feature"
    theirs_geojson["id"] = "theirs-feature"
    theirs_geojson["properties"]["id"] = 98002  # re-key: no longer conflicting
    resolution = {"type": "FeatureCollection",
                  "features": [ours_geojson, theirs_geojson]}
    path = tmp_path / "resolution.geojson"
    path.write_text(json.dumps(resolution))
    r = runner.invoke(
        cli,
        ["resolve", "nz_waca_adjustments:feature:98001", "--with-file", str(path)],
    )
    assert r.exit_code == 0, r.output

    from kart_tpu.core.repo import KartRepo
    from kart_tpu.merge.index import MergeIndex

    repo = KartRepo(str(src))
    mi = MergeIndex.read_from_repo(repo)
    assert len(mi.resolves["nz_waca_adjustments:feature:98001"]) == 2

    for label in sorted(mi.conflicts):
        if label not in mi.resolves:
            r = runner.invoke(cli, ["resolve", label, "--with=ours"])
            assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["merge", "--continue", "-m", "done"])
    assert r.exit_code == 0, r.output
    ds = repo.structure("HEAD").datasets["nz_waca_adjustments"]
    assert ds.get_feature([98001])["id"] == 98001
    assert ds.get_feature([98002])["id"] == 98002


def test_status_json_during_merge(repo_dir, runner):
    """`kart status -o json` in merging state carries the reference's
    merging context + summarise-2 conflict counts under kart.status/v1
    (reference: kart/status.py:33-44)."""
    make_conflict(runner, repo_dir)
    r = runner.invoke(cli, ["merge", "alt"])
    assert r.exit_code == 0
    r = runner.invoke(cli, ["status", "-o", "json"])
    assert r.exit_code == 0, r.output
    body = json.loads(r.output)["kart.status/v1"]
    assert body["state"] == "merging"
    assert body["conflicts"] == {"points": {"feature": 1}}
    assert body["merging"]["theirs"]["branch"] == "alt"
    assert body["merging"]["ours"]["branch"] == "main"


def test_full_conflicts_listing_byte_exact(tmp_path, monkeypatch):
    """The filtered full text listing reproduces the reference's own
    expected output byte-for-byte (tests/test_conflicts.py:
    test_list_conflicts, points fixture)."""
    from conftest import REF_DATA, extract_ref_archive

    if not os.path.isdir(os.path.join(REF_DATA, "conflicts")):
        pytest.skip("reference fixtures not available")
    src = extract_ref_archive(tmp_path, "conflicts/points.tgz")
    monkeypatch.chdir(src)
    runner = CliRunner()
    r = runner.invoke(cli, ["merge", "theirs_branch"])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["conflicts", "nz_pa_points_topo_150k:feature:3"])
    assert r.exit_code == 0, r.output
    L = "nz_pa_points_topo_150k"
    expected = [
        f"{L}:",
        f"    {L}:feature:",
        f"        {L}:feature:3:",
        f"            {L}:feature:3:ancestor:",
        "                                     fid = 3",
        "                                    geom = POINT(...)",
        "                                 t50_fid = 2426273",
        "                              name_ascii = Tauwhare Pa",
        "                              macronated = N",
        "                                    name = Tauwhare Pa",
        f"            {L}:feature:3:ours:",
        "                                     fid = 3",
        "                                    geom = POINT(...)",
        "                                 t50_fid = 2426273",
        "                              name_ascii = Tauwhare Pa",
        "                              macronated = N",
        "                                    name = ours_version",
        f"            {L}:feature:3:theirs:",
        "                                     fid = 3",
        "                                    geom = POINT(...)",
        "                                 t50_fid = 2426273",
        "                              name_ascii = Tauwhare Pa",
        "                              macronated = N",
        "                                    name = theirs_version",
        "",
    ]
    assert r.output.splitlines() == expected
