"""Upgrade: V2 (.sno-dataset, legacy 256^2 paths) -> V3 history rewrite
(reference: tests/test_upgrade.py over archived old-format repos)."""

import pytest

from kart_tpu.core.repo import KartRepo
from kart_tpu.core.tree_builder import TreeBuilder
from kart_tpu.models.dataset import Dataset2, Dataset3
from kart_tpu.models.paths import PathEncoder
from kart_tpu.models.schema import Schema
from kart_tpu.upgrade import UpgradeError, upgrade_in_place, upgrade_repo

V2_COLS = [
    {
        "id": "c1",
        "name": "fid",
        "dataType": "integer",
        "primaryKeyIndex": 0,
        "size": 64,
    },
    {"id": "c2", "name": "name", "dataType": "text"},
    {"id": "c3", "name": "rating", "dataType": "float", "size": 64},
]


def make_v2_repo(tmp_path, n=6):
    """Build a V2-format repo by hand: .sno-dataset dirname, legacy hex
    feature paths, two commits."""
    repo = KartRepo.init_repository(tmp_path / "v2repo")
    repo.config.set_many(
        {
            "user.name": "V2 author",
            "user.email": "v2@example.com",
            "kart.repostructure.version": "2",
        }
    )
    schema = Schema.from_column_dicts(V2_COLS)
    enc = PathEncoder.LEGACY_ENCODER

    tb = TreeBuilder(repo.odb)
    for path, data in Dataset2.new_dataset_meta_blobs(
        "mytable", schema, title="My V2 table", path_encoder=enc
    ):
        tb.insert(path, repo.odb.write_blob(data))
    prefix = f"mytable/{Dataset2.DATASET_DIRNAME}/{Dataset2.FEATURE_PATH}"
    for i in range(1, n + 1):
        pk_values, blob = schema.encode_feature_blob(
            {"fid": i, "name": f"row-{i}", "rating": i * 1.5}
        )
        tb.insert(prefix + enc.encode_pks_to_path(pk_values), repo.odb.write_blob(blob))
    from kart_tpu.core.objects import Signature

    # explicit author: the test asserts authorship survives the upgrade, so
    # don't let ambient GIT_AUTHOR_* env vars leak in
    sig = Signature.now("V2 author", "v2@example.com")
    tree1 = tb.flush()
    c1 = repo.create_commit(
        "HEAD", tree1, "v2 initial import", [], author=sig, committer=sig
    )

    tb2 = TreeBuilder(repo.odb, tree1)
    pk_values, blob = schema.encode_feature_blob(
        {"fid": n + 1, "name": "added-later", "rating": 0.5}
    )
    tb2.insert(
        prefix + enc.encode_pks_to_path(pk_values), repo.odb.write_blob(blob)
    )
    tree2 = tb2.flush()
    c2 = repo.create_commit(
        "HEAD", tree2, "v2 second commit", [c1], author=sig, committer=sig
    )
    return repo, c1, c2


def test_v2_repo_readable_as_v2(tmp_path):
    repo, _, _ = make_v2_repo(tmp_path)
    assert repo.version == 2
    ds = repo.datasets("HEAD")["mytable"]
    assert isinstance(ds, Dataset2)
    assert ds.feature_count == 7
    assert ds.get_feature([3])["name"] == "row-3"


def test_upgrade_in_place(tmp_path):
    repo, c1, c2 = make_v2_repo(tmp_path)
    old_blob_oids = {
        e.oid
        for _, e in repo.datasets("HEAD")["mytable"].feature_tree.walk_blobs()
    }
    commit_map = upgrade_in_place(repo)
    assert len(commit_map) == 2

    repo = KartRepo(repo.workdir)  # reopen: version config changed
    assert repo.version == 3
    ds = repo.datasets("HEAD")["mytable"]
    assert isinstance(ds, Dataset3) and not isinstance(ds, Dataset2)
    assert ds.feature_count == 7
    assert ds.get_feature([3]) == {"fid": 3, "name": "row-3", "rating": 4.5}

    # feature blob content is reused by content-address, not re-written
    new_blob_oids = {e.oid for _, e in ds.feature_tree.walk_blobs()}
    assert new_blob_oids == old_blob_oids

    # history shape preserved: 2 commits, messages + authorship intact
    commits = list(repo.walk_commits(repo.head_commit_oid))
    assert len(commits) == 2
    assert commits[0][1].message.startswith("v2 second commit")
    assert commits[0][1].author.name == "V2 author"
    # first commit is the mapped c1
    assert commits[1][0] == commit_map[c1]


def test_upgrade_to_new_repo(tmp_path):
    repo, c1, c2 = make_v2_repo(tmp_path)
    dest, commit_map = upgrade_repo(repo.workdir, tmp_path / "v3repo")
    assert dest.version == 3
    ds = dest.datasets("HEAD")["mytable"]
    assert ds.feature_count == 7
    assert ds.get_meta_item("title") == "My V2 table"
    # old repo untouched
    assert KartRepo(repo.workdir).version == 2
    assert len(list(dest.walk_commits(dest.head_commit_oid))) == 2


def test_upgrade_v3_refuses(tmp_path):
    from helpers import make_imported_repo

    repo, _ = make_imported_repo(tmp_path)
    with pytest.raises(UpgradeError, match="already"):
        upgrade_in_place(repo)


def test_upgrade_cli(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    repo, _, _ = make_v2_repo(tmp_path)
    runner = CliRunner()
    r = runner.invoke(cli, ["upgrade", "--in-place", repo.workdir])
    assert r.exit_code == 0, r.output
    assert "Upgraded 2 commits in place" in r.output


# -- legacy V0 / V1 -----------------------------------------------------------

V1_TABLE_INFO = [
    {"cid": 0, "name": "fid", "type": "INTEGER", "notnull": 1, "pk": 1},
    {"cid": 1, "name": "name", "type": "TEXT", "notnull": 0, "pk": 0},
    {"cid": 2, "name": "geom", "type": "POINT", "notnull": 0, "pk": 0},
]
SRS_4326 = {
    "srs_name": "WGS 84",
    "srs_id": 4326,
    "organization": "EPSG",
    "organization_coordsys_id": 4326,
    "definition": 'GEOGCS["WGS 84",DATUM["WGS_1984"]]',
}


def make_v1_repo(tmp_path):
    """Hand-built V1 (.sno-table) repo: msgpack blob per feature, json'd GPKG
    meta tables, fields/<name> -> column id."""
    import base64

    import msgpack

    from kart_tpu.core.objects import Signature
    from kart_tpu.core.serialise import json_pack
    from kart_tpu.geometry import Geometry

    repo = KartRepo.init_repository(tmp_path / "v1repo")
    repo.config.set_many(
        {
            "user.name": "V1 author",
            "user.email": "v1@example.com",
            # real sno-era repos carry the legacy key (or none at all —
            # tree detection covers that, tested separately)
            "sno.repository.version": "1",
            "kart.repostructure.version": "1",
        }
    )
    tb = TreeBuilder(repo.odb)
    inner = "mytable/.sno-table"
    meta = {
        "version": {"version": "1.0"},
        "primary_key": "fid",
        "sqlite_table_info": V1_TABLE_INFO,
        "gpkg_contents": {"identifier": "My V1 table", "description": "old"},
        "gpkg_geometry_columns": {
            "table_name": "mytable",
            "column_name": "geom",
            "geometry_type_name": "POINT",
            "srs_id": 4326,
            "z": 0,
            "m": 0,
        },
        "gpkg_spatial_ref_sys": [SRS_4326],
    }
    for name, value in meta.items():
        tb.insert(f"{inner}/meta/{name}", repo.odb.write_blob(json_pack(value)))
    for name, cid in (("fid", 0), ("name", 1), ("geom", 2)):
        tb.insert(
            f"{inner}/meta/fields/{name}", repo.odb.write_blob(json_pack(cid))
        )
    for i in range(1, 4):
        geom = Geometry.from_wkt(f"POINT({i} {i})")
        packed = msgpack.packb(
            {0: i, 1: f"v1-row-{i}", 2: msgpack.ExtType(71, bytes(geom))},
            use_bin_type=True,
        )
        leaf = base64.urlsafe_b64encode(msgpack.packb(i)).decode()
        tb.insert(
            f"{inner}/{i:02x}/{i:02x}/{leaf}", repo.odb.write_blob(packed)
        )
    sig = Signature.now("V1 author", "v1@example.com")
    tree = tb.flush()
    repo.create_commit("HEAD", tree, "v1 import", [], author=sig, committer=sig)
    return repo


def make_v0_repo(tmp_path):
    """Hand-built V0 repo: directory per feature, blob per attribute."""
    from kart_tpu.core.objects import Signature
    from kart_tpu.core.serialise import json_pack

    repo = KartRepo.init_repository(tmp_path / "v0repo")
    repo.config.set_many(
        {
            "user.name": "V0 author",
            "user.email": "v0@example.com",
            "kart.repostructure.version": "0",
        }
    )
    tb = TreeBuilder(repo.odb)
    meta = {
        "version": {"version": "0.0.1"},
        "sqlite_table_info": [
            {"cid": 0, "name": "fid", "type": "INTEGER", "notnull": 1, "pk": 1},
            {"cid": 1, "name": "name", "type": "TEXT", "notnull": 0, "pk": 0},
        ],
        "gpkg_contents": {"identifier": "My V0 table", "description": ""},
    }
    for name, value in meta.items():
        tb.insert(
            f"oldtable/meta/{name}", repo.odb.write_blob(json_pack(value))
        )
    uuids = [
        "0a0a0a0a-0000-0000-0000-00000000000%d" % i for i in range(1, 4)
    ]
    for i, uuid in enumerate(uuids, start=1):
        base = f"oldtable/features/{i:04x}/{uuid}"
        tb.insert(f"{base}/fid", repo.odb.write_blob(json_pack(i)))
        tb.insert(
            f"{base}/name", repo.odb.write_blob(json_pack(f"v0-row-{i}"))
        )
    sig = Signature.now("V0 author", "v0@example.com")
    tree = tb.flush()
    repo.create_commit("HEAD", tree, "v0 import", [], author=sig, committer=sig)
    return repo


def test_upgrade_v1_repo(tmp_path):
    repo = make_v1_repo(tmp_path)
    dest, commit_map = upgrade_repo(repo.workdir, tmp_path / "from_v1")
    assert len(commit_map) == 1
    ds = dest.datasets("HEAD")["mytable"]
    assert isinstance(ds, Dataset3)
    assert ds.feature_count == 3
    f = ds.get_feature([2])
    assert f["name"] == "v1-row-2"
    assert f["geom"].envelope() is not None
    assert ds.get_meta_item("title") == "My V1 table"
    assert ds.get_meta_item("description") == "old"
    schema = ds.schema
    assert [c.name for c in schema.columns] == ["fid", "name", "geom"]
    assert schema.pk_columns[0].name == "fid"
    geom_col = schema.first_geometry_column
    assert geom_col.extra_type_info["geometryType"].startswith("POINT")
    assert geom_col.extra_type_info["geometryCRS"] == "EPSG:4326"
    assert "EPSG:4326" in ds.crs_identifiers()


def test_upgrade_v0_repo(tmp_path):
    repo = make_v0_repo(tmp_path)
    dest, commit_map = upgrade_repo(repo.workdir, tmp_path / "from_v0")
    assert len(commit_map) == 1
    ds = dest.datasets("HEAD")["oldtable"]
    assert ds.feature_count == 3
    assert ds.get_feature([1])["name"] == "v0-row-1"
    assert ds.get_meta_item("title") == "My V0 table"


def test_detect_tree_version(tmp_path):
    """Version detection from the tree alone, for repos with no version in
    config (pre-config sno repos)."""
    from kart_tpu.upgrade.legacy import detect_tree_version

    v1 = make_v1_repo(tmp_path)
    tree = v1.odb.tree(v1.odb.read_commit(v1.refs.head_resolved()).tree)
    assert detect_tree_version(tree) == 1

    v0 = make_v0_repo(tmp_path)
    tree = v0.odb.tree(v0.odb.read_commit(v0.refs.head_resolved()).tree)
    assert detect_tree_version(tree) == 0


def test_upgrade_v1_preserves_sibling_attachments_and_null_fills(tmp_path):
    """Attachments beside .sno-table survive; feature blobs missing a column
    (added mid-history) upgrade with NULL for that column."""
    import base64

    import msgpack

    repo = make_v1_repo(tmp_path)
    head = repo.refs.head_resolved()
    old_tree = repo.odb.read_commit(head).tree
    tb = TreeBuilder(repo.odb, old_tree)
    tb.insert("mytable/notes.txt", repo.odb.write_blob(b"attachment survives"))
    # a feature written before column 1 ("name") existed
    packed = msgpack.packb({0: 9}, use_bin_type=True)
    leaf = base64.urlsafe_b64encode(msgpack.packb(9)).decode()
    tb.insert(
        f"mytable/.sno-table/09/09/{leaf}", repo.odb.write_blob(packed)
    )
    from kart_tpu.core.objects import Signature

    sig = Signature.now("V1 author", "v1@example.com")
    c2 = repo.create_commit(
        "HEAD", tb.flush(), "v1 second", [head], author=sig, committer=sig
    )

    dest, commit_map = upgrade_repo(repo.workdir, tmp_path / "from_v1_att")
    root = dest.odb.tree(dest.odb.read_commit(commit_map[c2]).tree)
    assert root.get("mytable/notes.txt").data == b"attachment survives"
    ds = dest.datasets("HEAD")["mytable"]
    assert ds.get_feature([9]) == {"fid": 9, "name": None, "geom": None}


# -- real reference legacy archives as oracles ------------------------------

from conftest import extract_ref_archive, needs_ref_fixtures


@needs_ref_fixtures
@pytest.mark.parametrize(
    "rel",
    ["v0/points0.snow.tgz", "v1/points.tgz", "v2.kart/points.tgz",
     "v2.sno/points.tgz"],
)
def test_upgrade_real_reference_archives(tmp_path, rel):
    """Every legacy generation the reference ships (v0 'snow', v1, v2 under
    both kart and sno branding) upgrades from the real packfile archives,
    deterministically: all four histories converge on the same V3 commits."""
    src = extract_ref_archive(tmp_path / "src", f"upgrade/{rel}")
    dest, commit_map = upgrade_repo(src, tmp_path / "upgraded")
    assert len(commit_map) == 2
    assert dest.head_commit_oid.startswith("551eec7")
    ds = dest.datasets("HEAD")["nz_pa_points_topo_150k"]
    assert ds.feature_count == 2143
    assert ds.get_feature(1)["t50_fid"] == 2426271


@needs_ref_fixtures
def test_upgrade_to_kart_branding(tmp_path, cli_runner):
    """A real Sno-era repo re-brands in place: .sno -> .kart, config keys
    renamed, history untouched (reference: kart upgrade-to-kart)."""
    import os

    from kart_tpu.cli import cli

    src = extract_ref_archive(tmp_path, "upgrade/v2.sno/points.tgz")
    r = cli_runner.invoke(cli, ["upgrade-to-kart", src])
    assert r.exit_code == 0, r.output
    assert os.path.isdir(os.path.join(src, ".kart"))
    assert not os.path.isdir(os.path.join(src, ".sno"))
    repo = KartRepo(src)
    assert repo.head_commit_oid.startswith("0c64d82")
    assert repo.version == 2  # branding only; V2->V3 is `kart upgrade`
    # idempotence guard
    r = cli_runner.invoke(cli, ["upgrade-to-kart", src])
    assert r.exit_code != 0


def test_upgrade_to_tidy(tmp_path, cli_runner):
    """A bare-style repo (gitdir contents at top level) becomes tidy-style."""
    import os
    import shutil

    from helpers import make_imported_repo

    repo, ds_path = make_imported_repo(tmp_path)
    bare_dir = tmp_path / "barestyle"
    shutil.copytree(repo.gitdir, bare_dir)
    probe = KartRepo(str(bare_dir))
    probe.config["core.bare"] = "false"
    assert probe.workdir is None  # bare-style before

    from kart_tpu.cli import cli

    r = cli_runner.invoke(cli, ["upgrade-to-tidy", str(bare_dir)])
    assert r.exit_code == 0, r.output
    assert os.path.isdir(bare_dir / ".kart")
    tidied = KartRepo(str(bare_dir))
    assert tidied.workdir is not None
    assert tidied.datasets("HEAD")[ds_path].feature_count == 10
