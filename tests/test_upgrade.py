"""Upgrade: V2 (.sno-dataset, legacy 256^2 paths) -> V3 history rewrite
(reference: tests/test_upgrade.py over archived old-format repos)."""

import pytest

from kart_tpu.core.repo import KartRepo
from kart_tpu.core.tree_builder import TreeBuilder
from kart_tpu.models.dataset import Dataset2, Dataset3
from kart_tpu.models.paths import PathEncoder
from kart_tpu.models.schema import Schema
from kart_tpu.upgrade import UpgradeError, upgrade_in_place, upgrade_repo

V2_COLS = [
    {
        "id": "c1",
        "name": "fid",
        "dataType": "integer",
        "primaryKeyIndex": 0,
        "size": 64,
    },
    {"id": "c2", "name": "name", "dataType": "text"},
    {"id": "c3", "name": "rating", "dataType": "float", "size": 64},
]


def make_v2_repo(tmp_path, n=6):
    """Build a V2-format repo by hand: .sno-dataset dirname, legacy hex
    feature paths, two commits."""
    repo = KartRepo.init_repository(tmp_path / "v2repo")
    repo.config.set_many(
        {
            "user.name": "V2 author",
            "user.email": "v2@example.com",
            "kart.repostructure.version": "2",
        }
    )
    schema = Schema.from_column_dicts(V2_COLS)
    enc = PathEncoder.LEGACY_ENCODER

    tb = TreeBuilder(repo.odb)
    for path, data in Dataset2.new_dataset_meta_blobs(
        "mytable", schema, title="My V2 table", path_encoder=enc
    ):
        tb.insert(path, repo.odb.write_blob(data))
    prefix = f"mytable/{Dataset2.DATASET_DIRNAME}/{Dataset2.FEATURE_PATH}"
    for i in range(1, n + 1):
        pk_values, blob = schema.encode_feature_blob(
            {"fid": i, "name": f"row-{i}", "rating": i * 1.5}
        )
        tb.insert(prefix + enc.encode_pks_to_path(pk_values), repo.odb.write_blob(blob))
    from kart_tpu.core.objects import Signature

    # explicit author: the test asserts authorship survives the upgrade, so
    # don't let ambient GIT_AUTHOR_* env vars leak in
    sig = Signature.now("V2 author", "v2@example.com")
    tree1 = tb.flush()
    c1 = repo.create_commit(
        "HEAD", tree1, "v2 initial import", [], author=sig, committer=sig
    )

    tb2 = TreeBuilder(repo.odb, tree1)
    pk_values, blob = schema.encode_feature_blob(
        {"fid": n + 1, "name": "added-later", "rating": 0.5}
    )
    tb2.insert(
        prefix + enc.encode_pks_to_path(pk_values), repo.odb.write_blob(blob)
    )
    tree2 = tb2.flush()
    c2 = repo.create_commit(
        "HEAD", tree2, "v2 second commit", [c1], author=sig, committer=sig
    )
    return repo, c1, c2


def test_v2_repo_readable_as_v2(tmp_path):
    repo, _, _ = make_v2_repo(tmp_path)
    assert repo.version == 2
    ds = repo.datasets("HEAD")["mytable"]
    assert isinstance(ds, Dataset2)
    assert ds.feature_count == 7
    assert ds.get_feature([3])["name"] == "row-3"


def test_upgrade_in_place(tmp_path):
    repo, c1, c2 = make_v2_repo(tmp_path)
    old_blob_oids = {
        e.oid
        for _, e in repo.datasets("HEAD")["mytable"].feature_tree.walk_blobs()
    }
    commit_map = upgrade_in_place(repo)
    assert len(commit_map) == 2

    repo = KartRepo(repo.workdir)  # reopen: version config changed
    assert repo.version == 3
    ds = repo.datasets("HEAD")["mytable"]
    assert isinstance(ds, Dataset3) and not isinstance(ds, Dataset2)
    assert ds.feature_count == 7
    assert ds.get_feature([3]) == {"fid": 3, "name": "row-3", "rating": 4.5}

    # feature blob content is reused by content-address, not re-written
    new_blob_oids = {e.oid for _, e in ds.feature_tree.walk_blobs()}
    assert new_blob_oids == old_blob_oids

    # history shape preserved: 2 commits, messages + authorship intact
    commits = list(repo.walk_commits(repo.head_commit_oid))
    assert len(commits) == 2
    assert commits[0][1].message.startswith("v2 second commit")
    assert commits[0][1].author.name == "V2 author"
    # first commit is the mapped c1
    assert commits[1][0] == commit_map[c1]


def test_upgrade_to_new_repo(tmp_path):
    repo, c1, c2 = make_v2_repo(tmp_path)
    dest, commit_map = upgrade_repo(repo.workdir, tmp_path / "v3repo")
    assert dest.version == 3
    ds = dest.datasets("HEAD")["mytable"]
    assert ds.feature_count == 7
    assert ds.get_meta_item("title") == "My V2 table"
    # old repo untouched
    assert KartRepo(repo.workdir).version == 2
    assert len(list(dest.walk_commits(dest.head_commit_oid))) == 2


def test_upgrade_v3_refuses(tmp_path):
    from helpers import make_imported_repo

    repo, _ = make_imported_repo(tmp_path)
    with pytest.raises(UpgradeError, match="already"):
        upgrade_in_place(repo)


def test_upgrade_cli(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from kart_tpu.cli import cli

    repo, _, _ = make_v2_repo(tmp_path)
    runner = CliRunner()
    r = runner.invoke(cli, ["upgrade", "--in-place", repo.workdir])
    assert r.exit_code == 0, r.output
    assert "Upgraded 2 commits in place" in r.output
