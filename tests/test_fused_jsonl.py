"""Fused json-lines materialisation (ISSUE 1 tentpole, part 2): the
columnar row plan + compiled per-legend serialisers must emit bytes
identical to the generic delta/dict/encoder path, across value types,
escaping edge cases, and delta shapes (insert/update/delete)."""

import io
import json
import math

import pytest

from helpers import edit_commit, make_imported_repo


def jsonl(repo, fused):
    import os

    from kart_tpu.diff.writers import JsonLinesDiffWriter

    os.environ["KART_FUSED_JSONL"] = "1" if fused else "0"
    try:
        out = io.StringIO()
        w = JsonLinesDiffWriter(repo, "HEAD^...HEAD", output_path=out)
        changed = w.write_diff()
    finally:
        os.environ.pop("KART_FUSED_JSONL", None)
    return out.getvalue(), changed


def test_fused_jsonl_byte_identical_mixed_deltas(tmp_path):
    from kart_tpu.geometry import Geometry

    repo, ds_path = make_imported_repo(tmp_path, n=30)
    ds = repo.datasets()[ds_path]
    edit_commit(
        repo, ds_path,
        inserts=[
            {"fid": 100, "geom": Geometry.from_wkt("POINT (1 2)"),
             "name": 'quote " backslash \\ newline \n unicode ☃', "rating": 1.25},
            {"fid": 101, "geom": None, "name": None, "rating": None},
        ],
        updates=[
            {**ds.get_feature([3]), "rating": float("inf")},
            {**ds.get_feature([4]), "rating": float("nan")},
            {**ds.get_feature([5]), "name": "\x00\x1f control"},
        ],
        deletes=[7, 8],
        message="mixed edits",
    )
    fused, changed1 = jsonl(repo, True)
    plain, changed2 = jsonl(repo, False)
    assert fused == plain
    assert changed1 is True and changed2 is True
    # sanity: every line parses, and NaN/Infinity came through as json.dumps
    # emits them
    lines = fused.strip().splitlines()
    assert any('"rating":Infinity' in ln for ln in lines)
    assert any('"rating":NaN' in ln for ln in lines)
    for ln in lines:
        json.loads(ln, parse_constant=lambda c: c)


def test_fused_columnar_fast_path_mixed_deltas(tmp_path):
    """A repo big enough to carry sidecars (>= SIDECAR_MIN_FEATURES) takes
    the columnar row-plan path in the fused writer; output must stay
    byte-identical to the delta path across inserts/updates/deletes."""
    from kart_tpu.diff.engine import get_feature_diff_rows
    from kart_tpu.geometry import Geometry

    repo, ds_path = make_imported_repo(tmp_path, n=12_000)
    ds = repo.datasets()[ds_path]
    edit_commit(
        repo, ds_path,
        inserts=[
            {"fid": 20_001, "geom": Geometry.from_wkt("POINT (5 6)"),
             "name": "inserted", "rating": 2.5},
        ],
        updates=[
            {**ds.get_feature([10]), "name": "upd"},
            {**ds.get_feature([11_999]), "rating": -1.0},
        ],
        deletes=[500, 501],
        message="mixed at sidecar scale",
    )
    base_rs = repo.structure("HEAD^")
    target_rs = repo.structure("HEAD")
    rows = get_feature_diff_rows(base_rs, target_rs, ds_path)
    assert rows is not None and rows["count"] == 5  # the fast path is live
    assert (rows["old_rows"] >= 0).sum() == 4  # updates + deletes
    assert (rows["new_rows"] >= 0).sum() == 3  # updates + insert
    fused, _ = jsonl(repo, True)
    plain, _ = jsonl(repo, False)
    assert fused == plain
    assert fused.count('"type":"feature"') == 5


def test_fanout_materialise_byte_identical(tmp_path, monkeypatch):
    """The fork-fanout materialiser (row range split over worker processes,
    outputs streamed back in order) emits exactly the serial bytes."""
    from kart_tpu.diff.writers import JsonLinesDiffWriter

    repo, ds_path = make_imported_repo(tmp_path, n=11_000)
    ds = repo.datasets()[ds_path]
    edit_commit(
        repo, ds_path,
        updates=[
            {**ds.get_feature([fid]), "name": f"u{fid}"}
            for fid in range(10, 60)
        ],
        deletes=[100],
        message="edits",
    )
    serial, _ = jsonl(repo, True)  # m=51 < FANOUT_MIN_ROWS: serial
    monkeypatch.setattr(JsonLinesDiffWriter, "FANOUT_MIN_ROWS", 2)
    monkeypatch.setenv("KART_FUSED_PROCS", "2")  # force workers on any box
    fanned, _ = jsonl(repo, True)
    assert fanned == serial


def test_fused_jsonl_no_changes(tmp_path):
    repo, ds_path = make_imported_repo(tmp_path, n=5)
    edit_commit(
        repo, ds_path,
        updates=[{**repo.datasets()[ds_path].get_feature([2]), "name": "x"}],
        message="one edit",
    )
    fused, _ = jsonl(repo, True)
    plain, _ = jsonl(repo, False)
    assert fused == plain


def test_serializer_matches_generic_dict_encoder(tmp_path):
    """feature_json_str_from_data == compact-JSON of feature_json_from_data
    for every feature blob in the repo (the unit-level parity the writer
    test exercises end-to-end)."""
    repo, ds_path = make_imported_repo(tmp_path, n=12)
    ds = repo.datasets()[ds_path]
    enc = json.JSONEncoder(separators=(",", ":"), ensure_ascii=True).encode
    feature_tree = ds.feature_tree
    odb = feature_tree.odb
    n = 0
    for path, entry in feature_tree.walk_blobs():
        pks = ds.decode_path_to_pks(path)
        data = odb.read_blob(entry.oid)
        fused = ds.feature_json_str_from_data(pks, data)
        generic = enc(ds.feature_json_from_data(pks, data))
        assert fused == generic, path
        n += 1
    assert n == 12


def test_attributes_dataset_fused(tmp_path):
    """Geometry-less datasets (int/str/bool columns) take the fused path
    too, byte-identically."""
    from kart_tpu.core.repo import KartRepo
    from kart_tpu.importer import ImportSource
    from kart_tpu.importer.importer import import_sources

    from helpers import create_attributes_gpkg

    gpkg = create_attributes_gpkg(str(tmp_path / "attrs.gpkg"), n=20)
    repo = KartRepo.init_repository(tmp_path / "repo")
    repo.config.set_many({"user.name": "T", "user.email": "t@example.com"})
    import_sources(repo, ImportSource.open(gpkg))
    ds_path = "records"
    # edit_commit assumes a 'fid' pk; this table's pk is 'id'
    from kart_tpu.diff.structs import (
        DatasetDiff,
        Delta,
        DeltaDiff,
        KeyValue,
        RepoDiff,
    )

    structure = repo.structure("HEAD")
    ds = structure.datasets[ds_path]
    feature_diff = DeltaDiff()
    for pk, change in ((2, {"code": "edited"}), (3, {"flag": False})):
        old = ds.get_feature([pk])
        feature_diff.add_delta(
            Delta.update(KeyValue((pk, old)), KeyValue((pk, {**old, **change})))
        )
    ds_diff = DatasetDiff()
    ds_diff["feature"] = feature_diff
    repo_diff = RepoDiff()
    repo_diff[ds_path] = ds_diff
    structure.commit_diff(repo_diff, "attr edits")
    fused, _ = jsonl(repo, True)
    plain, _ = jsonl(repo, False)
    assert fused == plain
